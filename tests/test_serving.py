"""Serving-tier tests (ISSUE 6): prepared-statement lifecycle with
aval-abstracted plan/executable reuse, admission control under overload,
the result cache, and graceful-shutdown queue draining.

Reference analogs: TestQueuesDb / resource-group tests in presto-tests,
TestPreparedStatements over DistributedQueryRunner, plus the serving
acceptance criteria: warm EXECUTE records compiles == 0 with no
parse/plan work; an overloaded group queues in policy order with zero
failures; shed queries get a clean QUEUE_FULL error; identical
re-submitted queries serve from the result cache checksum-equal."""

import json
import threading
import urllib.request

import numpy as np
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.catalog import MemoryTable
from presto_tpu.client import StatementClient, connect_http
from presto_tpu.client.statement import QueryError
from presto_tpu.server import PrestoTpuServer
from presto_tpu.server.resource_groups import (QueryRejected,
                                               ResourceGroupManager)
from presto_tpu.server.serving import ResultCache, ServingTier


def _session(**props):
    s = presto_tpu.connect(**props)
    s.catalog.register_memory(
        "t", {"k": T.BIGINT, "x": T.DOUBLE, "g": T.BIGINT, "s": T.VARCHAR},
        {"k": np.arange(200, dtype=np.int64),
         "x": np.arange(200, dtype=np.float64) * 1.5,
         "g": np.arange(200, dtype=np.int64) % 7,
         "s": np.array([f"val_{i:04d}" for i in range(200)], dtype=object)})
    return s


# ---------------------------------------------------------------------------
# prepared-statement lifecycle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["compiled", "dynamic"])
def test_prepared_lifecycle_zero_compile_warm(mode):
    """PREPARE -> EXECUTE (v1) -> EXECUTE (v2, differing values) ->
    re-EXECUTE: the warm binds record compiles == 0 AND no plan phase —
    parameter binding is a dict lookup plus device transfer."""
    s = _session(execution_mode=mode)
    s.sql("PREPARE pq FROM SELECT count(*) c, sum(x) v FROM t "
          "WHERE k < ? AND g = ?")
    r1 = s.sql("EXECUTE pq USING 120, 3")
    assert r1.rows == s.sql(
        "SELECT count(*) c, sum(x) v FROM t WHERE k < 120 AND g = 3").rows
    # warm: DIFFERENT parameter values, same type signature
    r2 = s.sql("EXECUTE pq USING 50, 5")
    assert r2.rows == s.sql(
        "SELECT count(*) c, sum(x) v FROM t WHERE k < 50 AND g = 5").rows
    assert r2.stats.compiles == 0
    assert r2.stats.prepared_binds == 1
    assert r2.stats.prepared_plan_hits == 1
    assert r2.stats.prepared_fallbacks == 0
    assert "plan" not in r2.stats.phase_ns  # no plan work on warm binds
    # re-EXECUTE previously seen values: still zero compiles
    r3 = s.sql("EXECUTE pq USING 120, 3")
    assert r3.stats.compiles == 0 and r3.stats.prepared_plan_hits == 1
    assert r3.rows == r1.rows
    # DEALLOCATE evicts; unknown names error cleanly
    s.sql("DEALLOCATE PREPARE pq")
    with pytest.raises(Exception, match="not found"):
        s.sql("EXECUTE pq USING 1, 1")
    with pytest.raises(Exception, match="not found"):
        s.sql("DEALLOCATE PREPARE pq")


def test_prepared_param_count_mismatch():
    s = _session()
    s.sql("PREPARE pq FROM SELECT count(*) FROM t WHERE k < ? AND g = ?")
    with pytest.raises(Exception, match="parameters"):
        s.sql("EXECUTE pq USING 1")
    with pytest.raises(Exception, match="parameters"):
        s.sql("EXECUTE pq USING 1, 2, 3")


def test_prepared_type_mismatch_errors_cleanly():
    s = _session()
    s.sql("PREPARE pq FROM SELECT count(*) FROM t WHERE x < ?")
    with pytest.raises(Exception):
        s.sql("EXECUTE pq USING 'not_a_number'")
    # the registry entry survives a failed bind
    assert s.sql("EXECUTE pq USING 3.0").rows[0][0] == 2


def test_prepared_varchar_params_fall_back_to_substitution():
    """String bindings cannot abstract to avals (device columns are
    dictionary-encoded); they take the substitution path, counted."""
    s = _session()
    s.sql("PREPARE pq FROM SELECT count(*) FROM t WHERE s = ?")
    r = s.sql("EXECUTE pq USING 'val_0007'")
    assert r.rows == [(1,)]
    assert r.stats.prepared_fallbacks == 1
    assert r.stats.prepared_binds == 0
    # quoting/escaping stays correct through the fallback
    assert s.sql("EXECUTE pq USING 'no''such'").rows == [(0,)]


def test_prepared_negative_and_date_params():
    s = _session()
    s.sql("PREPARE pq FROM SELECT count(*) FROM t WHERE k > ?")
    assert s.sql("EXECUTE pq USING -5").rows == [(200,)]
    cat = presto_tpu.connect()
    cat.catalog.register_memory(
        "d", {"dt": T.DATE},
        {"dt": np.array([0, 10_000, 20_000], dtype=np.int64)})
    cat.sql("PREPARE dq FROM SELECT count(*) FROM d WHERE dt < ?")
    r1 = cat.sql("EXECUTE dq USING DATE '1997-05-20'")  # day 10000 is 1997-05-19
    assert r1.rows == [(2,)]
    r2 = cat.sql("EXECUTE dq USING DATE '1970-01-02'")
    assert r2.rows == [(1,)] and r2.stats.compiles == 0


def test_prepared_limit_placeholder_uses_substitution():
    """`?` in a static grammar position (LIMIT) cannot stay symbolic:
    the registry marks the template subst-only and every EXECUTE
    substitutes text — correct results, value-keyed plans."""
    s = _session()
    s.sql("PREPARE pq FROM SELECT k FROM t ORDER BY k LIMIT ?")
    r = s.sql("EXECUTE pq USING 3")
    assert [x[0] for x in r.rows] == [0, 1, 2]
    assert r.stats.prepared_fallbacks == 1


def test_describe_input_infers_bound_types():
    s = _session()
    s.sql("PREPARE pq FROM SELECT k FROM t "
          "WHERE k > ? AND s LIKE ? AND x BETWEEN ? AND ?")
    rows = s.sql("DESCRIBE INPUT pq").rows
    assert rows == [(0, "bigint"), (1, "varchar"),
                    (2, "double"), (3, "double")]
    out = s.sql("DESCRIBE OUTPUT pq").rows
    assert out == [("k", "bigint")]


def test_execute_unknown_name():
    s = _session()
    with pytest.raises(Exception, match="not found"):
        s.sql("EXECUTE never_prepared USING 1")


def test_prepared_plan_value_free_across_catalog_write():
    """A catalog write bumps the version: the next EXECUTE replans
    (stale executables must not serve new data)."""
    s = _session(execution_mode="dynamic")
    s.sql("PREPARE pq FROM SELECT count(*) FROM t WHERE k < ?")
    assert s.sql("EXECUTE pq USING 100").rows == [(100,)]
    s.catalog.register_memory("u", {"a": T.BIGINT},
                              {"a": np.arange(3, dtype=np.int64)})
    r = s.sql("EXECUTE pq USING 100")  # version changed: fresh plan
    assert r.rows == [(100,)]


# ---------------------------------------------------------------------------
# result cache
# ---------------------------------------------------------------------------


def test_result_cache_unit():
    s = _session()
    rc = ResultCache(max_entries=4)
    cols = [{"name": "c", "type": "bigint"}]
    assert rc.get(s, "SELECT 1") is None
    assert rc.put(s, "SELECT 1", cols, [[1]])
    hit = rc.get(s, "SELECT 1")
    assert hit is not None and hit[1] == [[1]]
    # catalog version bump invalidates structurally (key miss)
    s.catalog.register_memory("v", {"a": T.BIGINT},
                              {"a": np.arange(2, dtype=np.int64)})
    assert rc.get(s, "SELECT 1") is None
    # volatile + non-SELECT statements never cache
    assert not rc.put(s, "SELECT now()", cols, [[1]])
    assert not rc.put(s, "INSERT INTO t VALUES (1)", cols, [[1]])
    rc.invalidate()
    assert rc.stats()["entries"] == 0


def test_result_cache_lru_and_bytes_bound():
    s = _session()
    rc = ResultCache(max_entries=2)
    cols = [{"name": "c", "type": "bigint"}]
    for i in range(4):
        rc.put(s, f"SELECT {i}", cols, [[i]])
    st = rc.stats()
    assert st["entries"] == 2 and st["evictions"] == 2
    # oversized results refuse the cache
    big = ResultCache(max_result_rows=2)
    assert not big.put(s, "SELECT 9", cols, [[1], [2], [3]])


def test_result_cache_table_scoped_invalidation():
    """Writes invalidate only the entries that reference the written
    table; everything else keeps serving (ISSUE 20 satellite)."""
    from presto_tpu.server.serving import referenced_tables, write_targets

    s = _session()
    s.catalog.register_memory("u", {"a": T.BIGINT},
                              {"a": np.arange(3, dtype=np.int64)})
    rc = ResultCache(max_entries=8)
    cols = [{"name": "c", "type": "bigint"}]
    assert rc.put(s, "SELECT count(*) FROM t", cols, [[200]])
    assert rc.put(s, "SELECT count(*) FROM u", cols, [[3]])
    assert rc.put(s, "SELECT 1", cols, [[1]])
    rc.invalidate(tables={"u"})
    assert rc.get(s, "SELECT count(*) FROM t") is not None
    assert rc.get(s, "SELECT count(*) FROM u") is None
    # provably table-free entries survive every scoped invalidation
    assert rc.get(s, "SELECT 1") is not None
    st = rc.stats()
    assert st["invalidationsScoped"] == 1
    assert st["invalidationsFull"] == 0
    rc.invalidate()  # no table set -> full clear
    assert rc.stats()["entries"] == 0
    assert rc.stats()["invalidationsFull"] == 1
    # the scoping helpers behind the cache
    assert "t" in referenced_tables("SELECT * FROM t JOIN u ON 1=1")
    assert "u" in referenced_tables("SELECT * FROM t JOIN u ON 1=1")
    assert write_targets("INSERT INTO u VALUES (1)") == frozenset({"u"})
    assert write_targets("REFRESH MATERIALIZED VIEW mv1") \
        == frozenset({"mv1"})
    assert write_targets("SELECT 1") is None


def test_result_cache_scoped_invalidation_through_server():
    """Protocol integration: a server write takes the SCOPED
    invalidation path (table set derived from the statement), not a
    full flush, and reads stay correct afterwards.  Locally the
    catalog-version cache key is the correctness backstop — the scoped
    drop is what rides the fleet broadcast so PEER coordinators (whose
    catalog version did not bump) keep serving unrelated entries."""
    s = _session()
    s.catalog.register_memory("u", {"a": T.BIGINT},
                              {"a": np.arange(3, dtype=np.int64)})
    srv = PrestoTpuServer(s).start()
    try:
        qt = "SELECT g, count(*) c FROM t GROUP BY g ORDER BY g"
        qu = "SELECT count(*) cu FROM u"
        first = connect_http(srv.uri).execute(qt).fetchall()
        connect_http(srv.uri).execute(qu).fetchall()
        connect_http(srv.uri).execute("INSERT INTO u VALUES (9)")
        info = json.loads(urllib.request.urlopen(
            f"{srv.uri}/v1/info").read())
        cache = info["serving"]["resultCache"]
        assert cache["invalidationsScoped"] >= 1
        assert cache["invalidationsFull"] == 0
        # correctness after the scoped drop: u recomputes fresh, t is
        # unchanged
        assert connect_http(srv.uri).execute(qu).fetchall() == [(4,)]
        assert connect_http(srv.uri).execute(qt).fetchall() == first
    finally:
        srv.stop()


def test_result_cache_serves_identical_query_checksum_equal():
    """Protocol integration: the identical re-submitted query serves
    from the cache with rows equal to the uncached execution."""
    s = _session()
    srv = PrestoTpuServer(s).start()
    try:
        q = "SELECT g, count(*) c, sum(x) v FROM t GROUP BY g ORDER BY g"
        first = connect_http(srv.uri).execute(q).fetchall()
        second = connect_http(srv.uri).execute(q).fetchall()
        assert first == second
        info = json.loads(urllib.request.urlopen(
            f"{srv.uri}/v1/info").read())
        assert info["serving"]["resultCache"]["hits"] >= 1
        # the cached execution shows up in history flagged as cached
        hist = json.loads(urllib.request.urlopen(
            f"{srv.uri}/v1/query").read())
        assert any(h["executionMode"] == "cached" for h in hist)
        # a write through the server invalidates explicitly
        connect_http(srv.uri).execute(
            "CREATE TABLE w AS SELECT k FROM t WHERE k < 3")
        info2 = json.loads(urllib.request.urlopen(
            f"{srv.uri}/v1/info").read())
        assert info2["serving"]["resultCache"]["invalidations"] >= 1
        third = connect_http(srv.uri).execute(q).fetchall()
        assert third == first
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def test_overload_queues_in_policy_order_zero_failures():
    """N sessions > the group's concurrency limit: every query
    completes, FIFO within the group, nothing fails."""
    s = _session()
    rgm = ResourceGroupManager()
    rgm.add_group("global.serve", hard_concurrency_limit=1,
                  max_queued=100)
    rgm.add_selector("global.serve")
    srv = PrestoTpuServer(s, resource_groups=rgm).start()
    results = {}
    order = []
    order_lock = threading.Lock()

    def run(i):
        cur = connect_http(srv.uri)
        cur.execute(f"SELECT count(*) FROM t WHERE k >= {i}")
        with order_lock:
            order.append(i)
        results[i] = cur.fetchall()

    try:
        threads = []
        for i in range(6):
            th = threading.Thread(target=run, args=(i,))
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=60)
        assert results == {i: [(200 - i,)] for i in range(6)}
        g = rgm._resolve("global.serve")
        assert g.total_admitted == 6 and g.total_rejected == 0
        assert g.running == 0 and g.queued == 0
    finally:
        srv.stop()


def test_shed_gets_clean_queue_full_error():
    s = _session()
    rgm = ResourceGroupManager()
    rgm.add_group("global.tiny", hard_concurrency_limit=1, max_queued=0)
    rgm.add_selector("global.tiny")
    srv = PrestoTpuServer(s, resource_groups=rgm).start()
    try:
        errors = []
        oks = []

        def run(i):
            try:
                cur = connect_http(srv.uri)
                cur.execute("SELECT count(*) FROM t, t t2 "
                            "WHERE t.k = t2.k")
                oks.append(i)
            except QueryError as e:
                errors.append(str(e))

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        assert oks  # at least the first one ran
        assert errors and all("Too many queued" in e for e in errors)
        info = json.loads(urllib.request.urlopen(
            f"{srv.uri}/v1/info").read())
        g = [x for x in info["resourceGroups"]
             if x["name"] == "global.tiny"][0]
        assert g["totalShed"] == len(errors)
        assert info["serving"]["shed"] == len(errors)
    finally:
        srv.stop()


def test_queue_full_error_code_in_payload():
    """The shed error carries the QUEUE_FULL code through the protocol
    payload (reference: QUERY_QUEUE_FULL error code in query JSON)."""
    s = _session()
    rgm = ResourceGroupManager()
    rgm.add_group("global.z", hard_concurrency_limit=1, max_queued=0)
    rgm.add_selector("global.z")
    srv = PrestoTpuServer(s, resource_groups=rgm).start()
    try:
        hold = rgm.acquire("u")  # saturate the group out-of-band
        job = srv.submit("SELECT 1")
        assert job.done.wait(timeout=30)
        payload = srv.results_payload(job, 0)
        assert payload["error"]["errorCode"] == "QUEUE_FULL"
        rgm.release(hold)
    finally:
        srv.stop()


def test_memory_budget_blocks_admission():
    rgm = ResourceGroupManager()
    rgm.add_group("global.m", hard_concurrency_limit=10,
                  soft_memory_limit_bytes=1 << 20)
    rgm.add_selector("global.m")
    g1 = rgm.acquire("u", memory_bytes=1 << 20)  # hits the limit
    with pytest.raises(QueryRejected):
        rgm.acquire("u", memory_bytes=1, timeout=0.1)
    rgm.release(g1, memory_bytes=1 << 20)
    g2 = rgm.acquire("u", memory_bytes=1)  # freed: admits again
    rgm.release(g2, memory_bytes=1)
    assert rgm._resolve("global.m").memory_reserved_bytes == 0


def test_admission_abort_drains_with_shutdown_code():
    rgm = ResourceGroupManager()
    rgm.add_group("global.a", hard_concurrency_limit=1, max_queued=10)
    rgm.add_selector("global.a")
    hold = rgm.acquire("u")
    flag = threading.Event()
    out = {}

    def waiter():
        try:
            rgm.acquire("u", timeout=30, abort=flag.is_set)
        except QueryRejected as e:
            out["code"] = e.code

    th = threading.Thread(target=waiter)
    th.start()
    while not rgm._resolve("global.a")._queue:
        pass
    flag.set()
    th.join(timeout=10)
    assert out.get("code") == "SERVER_SHUTTING_DOWN"
    rgm.release(hold)


# ---------------------------------------------------------------------------
# graceful shutdown drains the admission queue
# ---------------------------------------------------------------------------


class _SlowTable(MemoryTable):
    """MemoryTable whose reads block on an Event — deterministic
    long-running queries for drain tests."""

    def __init__(self, name, schema, data, gate):
        super().__init__(name, schema, data)
        self.gate = gate

    def read(self, columns=None, split=None):
        self.gate.wait(timeout=30)
        return super().read(columns, split)


def test_graceful_shutdown_cancels_queued_jobs_terminally():
    """Queued (admitted-but-not-started) jobs drain to a terminal
    CANCELED state their waiting clients can read; the running query
    completes (ISSUE 6 satellite: drain queued, not just running)."""
    gate = threading.Event()
    s = presto_tpu.connect(properties={"execution_mode": "dynamic"})
    s.catalog.register(_SlowTable(
        "slow", {"k": T.BIGINT},
        {"k": np.arange(10, dtype=np.int64)}, gate))
    rgm = ResourceGroupManager()
    rgm.add_group("global.one", hard_concurrency_limit=1, max_queued=10)
    rgm.add_selector("global.one")
    srv = PrestoTpuServer(s, resource_groups=rgm).start()
    try:
        running = StatementClient(srv.uri, "SELECT count(*) FROM slow")
        running.advance()
        run_job = srv.jobs[running.query_id]
        # wait until the first query holds the group slot
        deadline = threading.Event()
        for _ in range(200):
            if rgm._resolve("global.one").running == 1:
                break
            deadline.wait(timeout=0.02)
        queued = [StatementClient(srv.uri, f"SELECT count(*) + {i} "
                                  "FROM slow") for i in range(3)]
        for c in queued:
            c.advance()
        for _ in range(200):
            if rgm._resolve("global.one").queued == 3:
                break
            deadline.wait(timeout=0.02)
        assert rgm._resolve("global.one").queued == 3
        shut = threading.Thread(target=srv.graceful_shutdown,
                                kwargs={"timeout": 20}, daemon=True)
        shut.start()
        # queued jobs turn terminally CANCELED while the running one
        # still executes
        qjobs = [srv.jobs[c.query_id] for c in queued]
        for j in qjobs:
            assert j.done.wait(timeout=10)
            assert j.state == "CANCELED"
            assert "shutting down" in (j.error or "")
            assert j.error_code == "SERVER_SHUTTING_DOWN"
        assert run_job.state == "RUNNING"
        gate.set()  # release the running query; drain completes
        assert run_job.done.wait(timeout=20)
        assert run_job.state == "FINISHED"
        shut.join(timeout=20)
        assert not shut.is_alive()
    finally:
        gate.set()
        try:
            srv.stop()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# cluster coordinator admission
# ---------------------------------------------------------------------------


def test_cluster_coordinator_admission(monkeypatch):
    from presto_tpu.parallel.cluster import ClusterSession

    s = _session()
    rgm = ResourceGroupManager()
    rgm.add_group("global.c", hard_concurrency_limit=2)
    rgm.add_selector("global.c")
    cs = ClusterSession(s, [], resource_groups=rgm)

    class _R:
        rows = [(1,)]

    monkeypatch.setattr(ClusterSession, "_sql_attempts",
                        lambda self, text, ctx, mon=None: _R())
    cs.sql("SELECT 1")
    g = rgm._resolve("global.c")
    assert g.total_admitted == 1 and g.running == 0
    assert g.memory_reserved_bytes == 0
    st = s.last_stats
    assert st.resource_group == "global.c"
    assert st.admission_wait_ms >= 0.0


# ---------------------------------------------------------------------------
# the serving QPS gate (bench.py --serve artifact)
# ---------------------------------------------------------------------------


def test_serve_gate_units():
    import bench

    rec = {"platform": "cpu", "sf": 0.01, "failures": 0,
           "qps_per_chip": 100.0, "p99_ms": 200.0,
           "box_sort_ms": 100.0}
    assert bench._serve_gate(dict(rec), None).startswith("pass")
    committed = {"platform": "cpu", "sf": 0.01,
                 "qps_per_chip": 100.0, "p99_ms": 200.0,
                 "box_sort_ms": 100.0}
    assert bench._serve_gate(dict(rec), committed) == "pass"
    slow = dict(rec, qps_per_chip=10.0)
    assert bench._serve_gate(slow, committed).startswith("FAIL")
    spiky = dict(rec, p99_ms=900.0)
    assert bench._serve_gate(spiky, committed).startswith("FAIL")
    # box-fingerprint scaling: a box 2x slower than the committed one
    # halves the qps bar (70 qps passes where an equal box would FAIL)
    # and doubles the p99 bar
    slow_box = dict(rec, qps_per_chip=70.0, p99_ms=500.0,
                    box_sort_ms=200.0)
    assert bench._serve_gate(slow_box, committed) == "pass"
    # no fingerprint on the committed record -> absolute legs skipped
    assert bench._serve_gate(
        dict(rec, qps_per_chip=10.0),
        {k: v for k, v in committed.items() if k != "box_sort_ms"},
    ).startswith("pass (committed record has no box fingerprint")
    other = dict(committed, platform="tpu")
    assert bench._serve_gate(dict(rec), other).startswith("pass (no")
    failed = dict(rec, failures=3)
    assert bench._serve_gate(failed, committed).startswith("FAIL")


def test_mv_serve_gate_units():
    """SERVE_r04's gate (bench.py --serve --mv): correctness legs are
    absolute; the p99-flatness leg and the committed-record absolute
    leg are core-aware (a 1-core box cannot hide co-located refresh
    compute — the FLEET_GATE enforcement precedent)."""
    import bench

    rec = {"platform": "cpu", "cores": 4, "failures": 0,
           "wrong_results": 0, "unrouted": 0,
           "p99_steady_ms": 10.0, "p99_churn_ms": 12.0,
           "p99_flat_ratio": 1.2, "routed_ms": 1.0,
           "recompute_ms": 500.0, "routed_speedup": 500.0,
           "box_sort_ms": 100.0}
    committed = dict(rec)
    assert bench._mv_serve_gate(dict(rec), None).startswith("pass")
    assert bench._mv_serve_gate(dict(rec), committed) == "pass"
    for bad in ({"failures": 2}, {"wrong_results": 1}, {"unrouted": 1},
                {"routed_speedup": 3.0},
                {"p99_flat_ratio": 2.0, "p99_churn_ms": 20.0}):
        assert bench._mv_serve_gate(dict(rec, **bad),
                                    committed).startswith("FAIL"), bad
    # 1-core box: flatness measured, not enforced — but the
    # correctness legs stay absolute
    one_core = dict(rec, cores=1, p99_flat_ratio=2.0,
                    p99_churn_ms=20.0)
    out = bench._mv_serve_gate(one_core, committed)
    assert out.startswith("pass") and "not enforced" in out
    assert bench._mv_serve_gate(dict(one_core, wrong_results=1),
                                committed).startswith("FAIL")
    # absolute churn-p99 leg vs the committed record, box-scaled,
    # >=2 cores only
    spiky = dict(rec, p99_churn_ms=40.0, p99_flat_ratio=1.2)
    assert bench._mv_serve_gate(spiky, committed).startswith("FAIL")
    assert bench._mv_serve_gate(dict(spiky, cores=1),
                                committed).startswith("pass")


def test_serve_gate_registered_in_bench_artifact():
    """The committed SERVE record rides the default bench artifact (the
    gate exits 0 on committed records — re-measuring is --serve)."""
    import bench

    rec = bench.load_serve_record()
    assert rec is not None, "SERVE_r01.json must be committed"
    summary = bench.serve_gate_summary()
    assert summary["qps_per_chip"] > 0
    assert summary["p99_ms"] > 0
    assert str(summary["gate"]).startswith("pass")
    assert bench._percentile([1, 2, 3, 4], 0.5) == 3


# ---------------------------------------------------------------------------
# query coalescing (ISSUE 12): vmap-batched prepared execution
# ---------------------------------------------------------------------------


def _coalesce_session(**props):
    """Session with int/double/date/decimal columns — the q6-shape
    parameter dtypes the coalescer must carry bit-identically."""
    s = presto_tpu.connect(**dict({"query_coalescing": "on",
                                   "coalesce_window_ms": 250.0}, **props))
    n = 300
    s.catalog.register_memory(
        "cq", {"k": T.BIGINT, "x": T.DOUBLE, "dt": T.DATE,
               "p": T.decimal(12, 2), "q": T.BIGINT},
        {"k": np.arange(n, dtype=np.int64),
         "x": (np.arange(n, dtype=np.float64) * 0.37) % 11.0,
         "dt": 9_000 + np.arange(n, dtype=np.int64) % 900,
         "p": (np.arange(n, dtype=np.int64) * 173) % 100_000,  # unscaled
         "q": np.arange(n, dtype=np.int64) % 50})
    return s


_COALESCE_TEMPLATE = (
    "PREPARE cq6 FROM SELECT count(*) c, sum(p * x) r, sum(q) s "
    "FROM cq WHERE dt >= ? AND x < ? AND p BETWEEN ? AND ? AND k < ?")


def _execute_concurrently(s, sqls, window_open=None):
    """Issue `sqls` from one thread each, released together through a
    barrier so they land inside one coalescing window.  Returns results
    in submission order; raises the first worker error."""
    barrier = threading.Barrier(len(sqls))
    out = [None] * len(sqls)
    errs = []

    def run(i, sql):
        try:
            barrier.wait(timeout=30)
            out[i] = s.sql(sql)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=run, args=(i, q))
               for i, q in enumerate(sqls)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    if errs:
        raise errs[0]
    return out


def test_coalesced_equivalence_across_dtypes():
    """Batched-vs-solo checksum equivalence with int, double, date, and
    decimal parameters (q6-shape): a 4-wide batch returns exactly what
    four solo executions return, every rider records the batch size,
    and the warm batch compiles nothing."""
    s = _coalesce_session()
    s.sql(_COALESCE_TEMPLATE)
    binds = [("DATE '1995-01-01'", 8.5, "10.00", "700.00", 250),
             ("DATE '1996-06-15'", 3.25, "0.05", "999.99", 300),
             ("DATE '1994-12-31'", 10.0, "250.50", "251.50", 120),
             ("DATE '1995-07-04'", 1.0, "0.01", "900.00", 77)]
    execs = [f"EXECUTE cq6 USING {d}, {x}, {lo}, {hi}, {k}"
             for d, x, lo, hi, k in binds]
    solo = []
    s.set("query_coalescing", "off")
    for e in execs:
        solo.append(s.sql(e).rows)
    s.set("query_coalescing", "on")
    batched = _execute_concurrently(s, execs)
    for r, expect in zip(batched, solo):
        assert r.rows == expect
        assert r.stats.coalesced_batch_size == 4
        assert r.stats.execution_mode == "compiled"
    # warm: a second 4-wide batch with fresh values compiles NOTHING —
    # the pow2 bucket's executable replays from the memo
    binds2 = [f"EXECUTE cq6 USING DATE '1995-03-0{i + 1}', "
              f"{2.0 + i}, 1.0{i}, 88{i}.00, {40 + i}" for i in range(4)]
    warm = _execute_concurrently(s, binds2)
    for r in warm:
        assert r.stats.compiles == 0
        assert r.stats.coalesced_batch_size == 4
    s.set("query_coalescing", "off")
    for r, e in zip(warm, binds2):
        assert r.rows == s.sql(e).rows


def test_coalesce_batch_sizes_and_pow2_padding():
    """Size 2 batches exactly; size 3 pads to the pow2 bucket (4) and a
    following size-4 batch REUSES that bucket's executable: compiles ==
    0 for every member."""
    s = _coalesce_session()
    s.sql("PREPARE pk FROM SELECT count(*) c, sum(x) v FROM cq "
          "WHERE k < ?")
    two = _execute_concurrently(
        s, ["EXECUTE pk USING 120", "EXECUTE pk USING 55"])
    assert [r.rows for r in two] == [[(120, pytest.approx(
        sum((i * 0.37) % 11.0 for i in range(120))))], [(55, pytest.approx(
            sum((i * 0.37) % 11.0 for i in range(55))))]]
    assert all(r.stats.coalesced_batch_size == 2 for r in two)
    three = _execute_concurrently(
        s, [f"EXECUTE pk USING {k}" for k in (10, 20, 30)])
    assert all(r.stats.coalesced_batch_size == 3 for r in three)
    assert [r.rows[0][0] for r in three] == [10, 20, 30]
    four = _execute_concurrently(
        s, [f"EXECUTE pk USING {k}" for k in (11, 22, 33, 44)])
    assert [r.rows[0][0] for r in four] == [11, 22, 33, 44]
    assert all(r.stats.coalesced_batch_size == 4 for r in four)
    # 3 padded to 4 built the bucket; the true 4 replays it
    assert all(r.stats.compiles == 0 for r in four)


def test_coalesce_window_timeout_runs_solo():
    """A lone EXECUTE under forced coalescing waits out the window and
    runs solo: correct rows, batch size 0, the window wait recorded."""
    s = _coalesce_session(coalesce_window_ms=40.0)
    s.sql("PREPARE pk FROM SELECT count(*) FROM cq WHERE k < ?")
    r = s.sql("EXECUTE pk USING 100")
    assert r.rows == [(100,)]
    assert r.stats.coalesced_batch_size == 0
    assert r.stats.coalesce_ms >= 30.0  # paid the (empty) window
    c = s._query_coalescer.stats()
    assert c["windowTimeouts"] >= 1 and c["batches"] == 0


def test_mixed_signatures_never_co_batch():
    """Two different prepared signatures submitted concurrently batch
    only within their own signature — the group key is the template x
    type-signature fingerprint, so cross-batching is structural."""
    s = _coalesce_session()
    s.sql("PREPARE pa FROM SELECT count(*) c FROM cq WHERE k < ?")
    s.sql("PREPARE pb FROM SELECT sum(x) v FROM cq WHERE x < ?")
    rs = _execute_concurrently(s, [
        "EXECUTE pa USING 100", "EXECUTE pb USING 5.5",
        "EXECUTE pa USING 200", "EXECUTE pb USING 2.5"])
    assert rs[0].rows == [(100,)] and rs[2].rows == [(200,)]
    exp_b = [sum(v for i in range(300)
                 if (v := (i * 0.37) % 11.0) < lim) for lim in (5.5, 2.5)]
    assert rs[1].rows[0][0] == pytest.approx(exp_b[0])
    assert rs[3].rows[0][0] == pytest.approx(exp_b[1])
    for r in rs:
        assert r.stats.coalesced_batch_size <= 2  # own signature only


def test_coalesce_leader_fault_riders_rerun_solo():
    """Chaos: an injected fault kills the batch leader's launch — every
    member re-runs solo with correct results, zero surfaced failures,
    and the fallback is counted."""
    from presto_tpu.parallel import faults as F

    s = _coalesce_session()
    s.sql("PREPARE pk FROM SELECT count(*) FROM cq WHERE k < ?")
    F.install(F.FaultPlan.parse("coalesce:BATCH:*:1:fail"))
    try:
        rs = _execute_concurrently(
            s, [f"EXECUTE pk USING {k}" for k in (60, 70, 80)])
    finally:
        F.install(None)
    assert [r.rows for r in rs] == [[(60,)], [(70,)], [(80,)]]
    assert sum(r.stats.coalesce_fallbacks for r in rs) == 3
    c = s._query_coalescer.stats()
    assert c["fallbacks"] >= 1 and c["batches"] == 0
    # the harness is gone: the next batch coalesces normally
    rs2 = _execute_concurrently(
        s, [f"EXECUTE pk USING {k}" for k in (61, 71, 81)])
    assert [r.rows for r in rs2] == [[(61,)], [(71,)], [(81,)]]
    assert all(r.stats.coalesced_batch_size == 3 for r in rs2)


def test_result_cache_hit_accounting_unchanged_under_coalescing():
    """A coalesced batch populates the result cache per-rider (keyed by
    the substituted template text), identical re-submitted EXECUTE
    values hit BEFORE joining any batch, and the hit accounting is the
    same whether coalescing is on or off."""
    s = _coalesce_session()
    tier = ServingTier(s)  # installs the result cache + backref
    s.sql("PREPARE pk FROM SELECT count(*) FROM cq WHERE k < ?")
    first = s.sql("EXECUTE pk USING 90")  # solo (window timeout), stores
    assert first.rows == [(90,)]
    assert tier.result_cache.stats()["stores"] == 1
    hit = s.sql("EXECUTE pk USING 90")
    assert hit.rows == [(90,)]
    assert hit.stats.result_cache_hit == 1
    assert hit.stats.execution_mode == "cached"
    assert tier.result_cache.stats()["hits"] == 1
    # a concurrent wave of the SAME value: every member serves from the
    # cache without forming a batch
    before = s._query_coalescer.stats()["batches"]
    rs = _execute_concurrently(s, ["EXECUTE pk USING 90"] * 3)
    assert all(r.rows == [(90,)] and r.stats.result_cache_hit == 1
               for r in rs)
    assert tier.result_cache.stats()["hits"] == 4
    assert s._query_coalescer.stats()["batches"] == before
    # a coalesced batch of DISTINCT values stores per-rider
    stores0 = tier.result_cache.stats()["stores"]
    rs = _execute_concurrently(
        s, [f"EXECUTE pk USING {k}" for k in (31, 42, 53)])
    assert [r.rows[0][0] for r in rs] == [31, 42, 53]
    assert tier.result_cache.stats()["stores"] == stores0 + 3
    # ... and each re-submission now hits without executing
    again = s.sql("EXECUTE pk USING 42")
    assert again.rows == [(42,)] and again.stats.result_cache_hit == 1
    # coalescing OFF (separate session — the cache keys on the property
    # map): the store-then-hit accounting is identical
    s2 = _coalesce_session(query_coalescing="off")
    tier2 = ServingTier(s2)
    s2.sql("PREPARE pk FROM SELECT count(*) FROM cq WHERE k < ?")
    s2.sql("EXECUTE pk USING 90")
    off = s2.sql("EXECUTE pk USING 90")
    assert off.rows == [(90,)] and off.stats.result_cache_hit == 1
    assert tier2.result_cache.stats()["stores"] == 1
    assert tier2.result_cache.stats()["hits"] == 1


def test_serving_tier_embedded_admission():
    """ServingTier.admit/release work embedded (no HTTP): the surface
    bench.py --serve and the protocol server share."""
    s = _session()
    rgm = ResourceGroupManager()
    rgm.add_group("global.e", hard_concurrency_limit=1, max_queued=5)
    rgm.add_selector("global.e")
    tier = ServingTier(s, resource_groups=rgm)
    slot = tier.admit("u", "src")
    assert slot is not None and slot.group.full_name == "global.e"
    assert tier.queries_admitted == 1
    tier.release(slot, cpu_s=0.01)
    assert rgm._resolve("global.e").running == 0
    # no resource groups configured -> admission disabled, not an error
    assert ServingTier(s).admit("u") is None
