"""Approximate aggregates + extended function library tests
(reference analogs: TestApproximateCountDistinct, TestMathFunctions,
TestStringFunctions, TestDateTimeFunctions in presto-main)."""

import math

import numpy as np
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.catalog import Catalog, MemoryTable


@pytest.fixture(scope="module")
def session():
    rng = np.random.default_rng(7)
    n = 50_000
    cat = Catalog()
    cat.register(MemoryTable(
        "t",
        {"g": T.BIGINT, "k": T.BIGINT, "x": T.DOUBLE, "s": T.VARCHAR,
         "d": T.DATE},
        {"g": rng.integers(0, 4, n),
         "k": rng.integers(0, 5000, n),
         "x": rng.random(n) * 100,
         "s": np.array([f"val_{v:04d}" for v in rng.integers(0, 300, n)],
                       dtype=object),
         "d": rng.integers(8000, 12000, n).astype(np.int32)}))
    return presto_tpu.connect(cat)


def test_approx_distinct_accuracy(session):
    exact = session.sql("SELECT count(DISTINCT k) FROM t").rows[0][0]
    approx = session.sql("SELECT approx_distinct(k) FROM t").rows[0][0]
    assert abs(approx - exact) / exact < 0.12  # m=1024 -> ~3.25% stderr
    # grouped
    rows = session.sql(
        "SELECT g, approx_distinct(k), count(DISTINCT k) FROM t "
        "GROUP BY g ORDER BY g").rows
    for _, ap, ex in rows:
        assert abs(ap - ex) / ex < 0.15


def test_approx_distinct_strings(session):
    exact = session.sql("SELECT count(DISTINCT s) FROM t").rows[0][0]
    approx = session.sql("SELECT approx_distinct(s) FROM t").rows[0][0]
    assert abs(approx - exact) / exact < 0.15


def test_approx_percentile(session):
    x = session.sql("SELECT approx_percentile(x, 0.5) FROM t").rows[0][0]
    assert abs(x - 50.0) < 2.0  # uniform [0, 100)
    rows = session.sql(
        "SELECT g, approx_percentile(x, 0.9) FROM t GROUP BY g").rows
    for _, v in rows:
        assert abs(v - 90.0) < 3.0


def test_min_by_max_by(session):
    r = session.sql("SELECT max_by(s, k), min_by(s, k) FROM t").rows[0]
    km = session.sql("SELECT max(k), min(k) FROM t").rows[0]
    # ties on the key are broken arbitrarily (Presto semantics): the
    # result must be one of the tied rows' values
    hi = {x[0] for x in session.sql(
        f"SELECT s FROM t WHERE k = {km[0]}").rows}
    lo = {x[0] for x in session.sql(
        f"SELECT s FROM t WHERE k = {km[1]}").rows}
    assert r[0] in hi and r[1] in lo


def test_checksum_order_independent(session):
    a = session.sql("SELECT checksum(k) FROM t").rows[0][0]
    b = session.sql("SELECT checksum(k) FROM (SELECT k FROM t ORDER BY x) AS q"
                    ).rows[0][0]
    assert a == b
    c = session.sql("SELECT checksum(k + 1) FROM t").rows[0][0]
    assert a != c


def test_geometric_mean(session):
    g = session.sql("SELECT geometric_mean(x) FROM t WHERE x > 0").rows[0][0]
    am = session.sql("SELECT avg(ln(x)) FROM t WHERE x > 0").rows[0][0]
    assert abs(g - math.exp(am)) < 1e-6 * g


@pytest.mark.parametrize("expr,expected", [
    ("sin(0)", 0.0), ("cos(0)", 1.0), ("atan2(1, 1)", math.pi / 4),
    ("cbrt(27)", 3.0), ("degrees(pi())", 180.0), ("radians(180) - pi()", 0.0),
    ("log(2, 8)", 3.0), ("log2(32)", 5.0), ("truncate(3.99)", 3.0),
    ("truncate(-3.99)", -3.0), ("width_bucket(35, 0, 100, 10)", 4),
    ("bitwise_and(12, 10)", 8), ("bitwise_or(12, 10)", 14),
    ("bitwise_xor(12, 10)", 6), ("bitwise_not(0)", -1),
    ("bitwise_left_shift(1, 10)", 1024), ("bitwise_right_shift(1024, 3)", 128),
])
def test_math_scalars(session, expr, expected):
    v = session.sql(f"SELECT {expr}").rows[0][0]
    assert abs(float(v) - float(expected)) < 1e-9


@pytest.mark.parametrize("expr,expected", [
    ("lpad('7', 3, '0')", "007"), ("rpad('ab', 4, 'x')", "abxx"),
    ("repeat('ab', 3)", "ababab"), ("split_part('a,b,c', ',', 2)", "b"),
    ("position('abc', 'c')", 3),
    ("codepoint('A')", 65), ("chr(66)", "B"),
    ("regexp_extract('presto-1234-tpu', '[0-9]+')", "1234"),
    ("regexp_replace('a1b2', '[0-9]', '_')", "a_b_"),
])
def test_string_scalars(session, expr, expected):
    v = session.sql(f"SELECT {expr}").rows[0][0]
    assert v == expected


def test_string_functions_on_columns(session):
    rows = session.sql(
        "SELECT count(*) FROM t WHERE regexp_like(s, 'val_00[0-9][0-9]')"
    ).rows
    exact = session.sql("SELECT count(*) FROM t WHERE k >= 0 AND "
                        "substr(s, 5, 2) = '00'").rows
    assert rows[0][0] == exact[0][0]
    r2 = session.sql("SELECT split_part(s, '_', 2) AS p, count(*) FROM t "
                     "GROUP BY 1 ORDER BY 2 DESC LIMIT 1").rows
    assert len(r2) == 1 and len(r2[0][0]) == 4


def test_date_functions(session):
    rows = session.sql(
        "SELECT d, date_trunc('month', d) AS m, day_of_week(d) AS dw, "
        "day_of_year(d) AS dy, last_day_of_month(d) AS ld "
        "FROM t LIMIT 200").rows
    for d, m, dw, dy, ld in rows:
        dd = np.datetime64("1970-01-01") + np.timedelta64(int(d), "D")
        first = dd.astype("datetime64[M]").astype("datetime64[D]")
        assert (np.datetime64("1970-01-01") + np.timedelta64(int(m), "D")) == first
        iso = (int(d) + 3) % 7 + 1
        assert dw == iso
        assert dy == int((dd - first.astype("datetime64[Y]").astype("datetime64[D]"))
                         / np.timedelta64(1, "D")) + 1
        nxt = (first.astype("datetime64[M]") + 1).astype("datetime64[D]")
        assert (np.datetime64("1970-01-01") + np.timedelta64(int(ld), "D")) \
            == nxt - np.timedelta64(1, "D")


def test_date_diff(session):
    r = session.sql("SELECT date_diff('day', DATE '2020-01-01', "
                    "DATE '2020-03-01')").rows[0][0]
    assert r == 60
    # complete periods only (Presto/Joda semantics)
    r = session.sql("SELECT date_diff('month', DATE '2020-01-15', "
                    "DATE '2020-03-01')").rows[0][0]
    assert r == 1
    r = session.sql("SELECT date_diff('month', DATE '2024-01-31', "
                    "DATE '2024-02-01')").rows[0][0]
    assert r == 0
    r = session.sql("SELECT date_diff('year', DATE '1999-06-01', "
                    "DATE '2002-01-01')").rows[0][0]
    assert r == 2
    r = session.sql("SELECT date_diff('month', DATE '2020-03-01', "
                    "DATE '2020-01-15')").rows[0][0]
    assert r == -1


def test_date_semantics_review_fixes(session):
    # Joda end-of-month clamping
    assert session.sql("SELECT date_diff('month', DATE '2020-01-31', "
                       "DATE '2020-02-29')").rows[0][0] == 1
    assert session.sql("SELECT date_diff('year', DATE '2020-02-29', "
                       "DATE '2021-02-28')").rows[0][0] == 1
    # ISO week numbering
    assert session.sql("SELECT week(DATE '2017-01-01')").rows[0][0] == 52
    assert session.sql("SELECT week(DATE '2021-01-04')").rows[0][0] == 1
    assert session.sql("SELECT week(DATE '2020-12-31')").rows[0][0] == 53
    # regexp_replace group refs and literals
    assert session.sql(
        "SELECT regexp_replace('abc', 'b', '[$0]')").rows[0][0] == "a[b]c"
    assert session.sql(
        "SELECT regexp_replace('a1b', '([0-9])', '<$1>')").rows[0][0] == "a<1>b"
    assert session.sql(
        "SELECT regexp_replace('x', 'x', 'a$b')").rows[0][0] == "a$b"


def test_multiple_distinct_columns(session):
    r = session.sql(
        "SELECT g, count(DISTINCT k), count(DISTINCT s), sum(DISTINCT k), "
        "count(*) FROM t GROUP BY g ORDER BY g").rows
    for g, dk, ds, sk, c in r:
        ek = session.sql(f"SELECT count(DISTINCT k), sum(DISTINCT k) "
                         f"FROM t WHERE g = {g}").rows[0]
        es = session.sql(f"SELECT count(DISTINCT s) FROM t WHERE g = {g}"
                         ).rows[0][0]
        assert (dk, sk) == ek and ds == es


def test_prepared_statements(session):
    session.sql("PREPARE q1 FROM SELECT count(*) FROM t WHERE k < ? AND g = ?")
    a = session.sql("EXECUTE q1 USING 1000, 2").rows
    b = session.sql("SELECT count(*) FROM t WHERE k < 1000 AND g = 2").rows
    assert a == b
    c = session.sql("EXECUTE q1 USING 50, 0").rows
    d = session.sql("SELECT count(*) FROM t WHERE k < 50 AND g = 0").rows
    assert c == d
    # string params quote/escape correctly
    session.sql("PREPARE q2 FROM SELECT count(*) FROM t WHERE s = ?")
    e = session.sql("EXECUTE q2 USING 'val_0007'").rows
    f = session.sql("SELECT count(*) FROM t WHERE s = 'val_0007'").rows
    assert e == f and e[0][0] > 0
    session.sql("DEALLOCATE PREPARE q1")
    import pytest as _pytest
    with _pytest.raises(Exception, match="not found"):
        session.sql("EXECUTE q1 USING 1, 1")


def test_rollup_matches_manual_union(session):
    a = session.sql(
        "SELECT g, k % 3 AS k3, sum(x) AS s FROM t "
        "GROUP BY ROLLUP (g, k % 3) ORDER BY 1, 2, 3").rows
    b = session.sql(
        "SELECT g, k % 3 AS k3, sum(x) AS s FROM t GROUP BY g, k % 3 "
        "UNION ALL SELECT g, NULL, sum(x) FROM t GROUP BY g "
        "UNION ALL SELECT NULL, NULL, sum(x) FROM t "
        "ORDER BY 1, 2, 3").rows
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert ra[:2] == rb[:2] and abs(ra[2] - rb[2]) < 1e-6


def test_cube_and_grouping_sets(session):
    cube = session.sql("SELECT g, k % 2 AS k2, count(*) FROM t "
                       "GROUP BY CUBE (g, k % 2) ORDER BY 1, 2").rows
    # 4 groups x 2 + 4 + 2 + 1 = 15 rows for 4 g-values and 2 k2-values
    assert len(cube) == 15
    total = [r for r in cube if r[0] is None and r[1] is None]
    assert total[0][2] == 50_000
    gs = session.sql(
        "SELECT g, k % 2 AS k2, count(*) FROM t "
        "GROUP BY GROUPING SETS ((g), (k % 2), ()) ORDER BY 1, 2").rows
    assert len(gs) == 4 + 2 + 1


def test_quantified_comparisons(tpch_catalog_tiny):
    import presto_tpu as pt

    s = pt.connect(tpch_catalog_tiny)
    a = s.sql("SELECT count(*) FROM nation WHERE n_regionkey = ANY "
              "(SELECT r_regionkey FROM region WHERE r_name LIKE 'A%')").rows
    b = s.sql("SELECT count(*) FROM nation WHERE n_regionkey IN "
              "(SELECT r_regionkey FROM region WHERE r_name LIKE 'A%')").rows
    assert a == b
    g = s.sql("SELECT count(*) FROM nation WHERE n_regionkey <> ALL "
              "(SELECT r_regionkey FROM region WHERE r_name = 'ASIA')").rows
    h = s.sql("SELECT count(*) FROM nation WHERE n_regionkey NOT IN "
              "(SELECT r_regionkey FROM region WHERE r_name = 'ASIA')").rows
    assert g == h
    mx = s.sql("SELECT max(o_totalprice) FROM orders "
               "WHERE o_orderpriority = '1-URGENT'").rows[0][0]
    c = s.sql("SELECT count(*) FROM orders WHERE o_totalprice > ALL "
              "(SELECT o_totalprice FROM orders "
              "WHERE o_orderpriority = '1-URGENT')").rows
    d = s.sql(f"SELECT count(*) FROM orders WHERE o_totalprice > {mx}").rows
    assert c == d
    # vacuous ALL over an empty subquery is TRUE
    assert s.sql("SELECT count(*) FROM nation WHERE n_nationkey > ALL "
                 "(SELECT r_regionkey FROM region WHERE r_name = 'zzz')"
                 ).rows == [(25,)]
    # ANY/SOME words remain usable as identifiers
    assert s.sql("SELECT 1 AS any, 2 AS some").rows == [(1, 2)]


def test_quantified_null_and_empty_semantics(tpch_catalog_tiny):
    import presto_tpu as pt

    s = pt.connect(tpch_catalog_tiny)
    # NULL in the ALL-set: never definitely true
    assert s.sql("SELECT count(*) FROM (VALUES (5)) AS t(x) WHERE x > ALL "
                 "(SELECT nullif(v, 2) FROM (VALUES (1),(2)) AS s(v))"
                 ).rows == [(0,)]
    # ANY over empty is FALSE, stable under NOT
    assert s.sql("SELECT count(*) FROM (VALUES (5)) AS t(x) WHERE NOT "
                 "(x < ANY (SELECT v FROM (VALUES (1)) AS s(v) "
                 "WHERE v > 100))").rows == [(1,)]
    # any/some still usable as column names on a comparison RHS
    assert s.sql("SELECT x = some FROM (VALUES (1, 1)) AS t(x, some)"
                 ).rows == [(True,)]


def test_quantified_three_valued_logic(tpch_catalog_tiny):
    """SQL:2016 8.9 decision table incl. NULL results under negation
    (reference: TestQuantifiedComparisons semantics)."""
    import presto_tpu as pt

    s = pt.connect(tpch_catalog_tiny)
    cases = [
        ("SELECT 1 < ALL (SELECT v FROM (VALUES 2, NULL) t(v))", None),
        ("SELECT 3 < ALL (SELECT v FROM (VALUES 2, NULL) t(v))", False),
        ("SELECT 3 > ANY (SELECT v FROM (VALUES 5, NULL) t(v))", None),
        ("SELECT 6 > ANY (SELECT v FROM (VALUES 5, NULL) t(v))", True),
        ("SELECT 5 = ALL (SELECT v FROM (VALUES 5, 5) t(v))", True),
        ("SELECT 5 = ALL (SELECT v FROM (VALUES 5, 6) t(v))", False),
        ("SELECT 5 = ALL (SELECT v FROM (VALUES 5, NULL) t(v))", None),
        ("SELECT 5 <> ANY (SELECT v FROM (VALUES 5, 6) t(v))", True),
        ("SELECT 5 <> ANY (SELECT v FROM (VALUES 5, 5) t(v))", False),
        ("SELECT 5 <> ANY (SELECT v FROM (VALUES 5, NULL) t(v))", None),
        ("SELECT NULL < ALL (SELECT v FROM (VALUES 1) t(v))", None),
    ]
    for q, want in cases:
        assert s.sql(q).rows == [(want,)], q
    # a NULL quantified result must NOT become TRUE under NOT
    assert s.sql(
        "SELECT count(*) FROM (VALUES 1) WHERE NOT "
        "(1 < ALL (SELECT v FROM (VALUES 2, NULL) t(v)))").rows == [(0,)]


def test_exportable_hll_sketches(tpch_catalog_tiny):
    """Serializable HLL: approx_set/merge/cardinality + base64 export
    (reference: HyperLogLogFunctions + MergeHyperLogLogAggregation)."""
    import presto_tpu as pt

    s = pt.connect(tpch_catalog_tiny)
    est, exact = s.sql(
        "SELECT cardinality(approx_set(c_custkey)), count(DISTINCT c_custkey)"
        " FROM customer").rows[0]
    assert abs(est - exact) <= 0.1 * exact
    # merge of per-group sketches == sketch of the union
    merged = s.sql(
        "SELECT cardinality(merge(h)) FROM (SELECT c_nationkey, "
        "approx_set(c_custkey) AS h FROM customer GROUP BY c_nationkey)"
    ).rows[0][0]
    assert merged == est
    # export through text and back
    rt = s.sql(
        "SELECT cardinality(CAST(t AS HLL)) FROM (SELECT "
        "CAST(approx_set(c_custkey) AS VARCHAR) AS t FROM customer)"
    ).rows[0][0]
    assert rt == est
    assert s.sql("SELECT cardinality(empty_approx_set())").rows == [(0,)]


def test_qdigest(tpch_catalog_tiny):
    """qdigest_agg / value_at_quantile / quantile_at_value / merge
    (reference: QuantileDigestAggregationFunction + Functions)."""
    import presto_tpu as pt

    s = pt.connect(tpch_catalog_tiny)
    med, ref = s.sql(
        "SELECT value_at_quantile(qdigest_agg(o_totalprice), 0.5), "
        "approx_percentile(o_totalprice, 0.5) FROM orders").rows[0]
    assert abs(med - ref) <= 0.05 * ref
    q = s.sql(
        "SELECT quantile_at_value(qdigest_agg(o_totalprice), "
        f"{ref}) FROM orders").rows[0][0]
    assert 0.4 <= q <= 0.6
    vs = s.sql(
        "SELECT values_at_quantiles(qdigest_agg(o_totalprice), "
        "ARRAY[0.1, 0.9]) FROM orders").rows[0][0]
    assert vs[0] < med < vs[1]
    merged = s.sql(
        "SELECT value_at_quantile(merge(d), 0.5) FROM (SELECT "
        "o_orderpriority, qdigest_agg(o_totalprice) AS d FROM orders "
        "GROUP BY o_orderpriority)").rows[0][0]
    assert abs(merged - ref) <= 0.08 * ref


def test_json_distinct_type(tpch_catalog_tiny):
    """JSON as a distinct logical type (reference: spi/type/JsonType):
    json_parse canonicalizes, json_format renders, CAST re-tags."""
    import presto_tpu as pt

    s = pt.connect(tpch_catalog_tiny)
    assert s.sql("SELECT json_parse('{\"b\": 1,  \"a\": [1, 2]}')").rows \
        == [('{"b":1,"a":[1,2]}',)]
    assert s.sql(
        "SELECT json_extract_scalar(json_parse('{\"a\": 5}'), '$.a')"
    ).rows == [("5",)]
    # CAST wraps the varchar as a JSON *string value* (reference JsonType
    # cast); json_parse is the way to parse a document
    assert s.sql("SELECT CAST('abc' AS JSON)").rows == [('"abc"',)]
    assert s.sql("SELECT CAST(CAST('abc' AS JSON) AS VARCHAR)").rows \
        == [("abc",)]
    assert s.sql("SELECT is_json_scalar(json_parse('3'))").rows == [(True,)]
    with pytest.raises(Exception):
        s.sql("SELECT json_parse('{bad json')")


def test_wide_decimal_declarations(tpch_catalog_tiny):
    """DECIMAL(p>18) is two-limb Int128 (exec/dec128.py): values past 19
    significant digits are EXACT, not rejected; only the 38-digit
    boundary errors (reference: UnscaledDecimal128Arithmetic limits).
    Full exactness coverage: tests/test_decimal128.py."""
    from decimal import Decimal

    import presto_tpu as pt

    s = pt.connect(tpch_catalog_tiny)
    assert s.sql("SELECT CAST('12345678901234.56' AS DECIMAL(38,2)) "
                 "+ CAST('0.44' AS DECIMAL(38,2))").rows \
        == [(Decimal("12345678901235.00"),)]
    assert s.sql(
        "SELECT CAST('123456789012345678901234.5' AS DECIMAL(38,2))"
    ).rows == [(Decimal("123456789012345678901234.50"),)]
    assert s.sql(
        "SELECT CAST(4e9 AS DECIMAL(38,2)) * CAST(4e9 AS DECIMAL(38,2))"
    ).rows == [(Decimal(4_000_000_000) * Decimal(4_000_000_000),)]
    assert s.sql("SELECT TRY_CAST('1" + "0" * 38 + "' AS DECIMAL(38,0))"
                 ).rows == [(None,)]
