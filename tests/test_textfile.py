"""CSV / JSON-lines tables (presto-record-decoder role)."""

import numpy as np
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.catalog import Catalog


def test_csv_infer_and_query(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("id,name,score,flag\n"
                 "1,alice,9.5,true\n"
                 "2,bob,,false\n"
                 "3,,7.25,true\n")
    cat = Catalog()
    cat.register_csv("t", str(p))
    s = presto_tpu.connect(cat)
    t = cat.get("t")
    assert t.schema["id"] == T.BIGINT
    assert t.schema["score"] == T.DOUBLE
    assert t.schema["flag"] == T.BOOLEAN
    assert s.sql("SELECT count(*), count(score), count(name) "
                 "FROM t").rows == [(3, 2, 2)]
    assert s.sql("SELECT id FROM t WHERE flag ORDER BY id").rows \
        == [(1,), (3,)]
    assert s.sql("SELECT sum(score) FROM t").rows[0][0] \
        == pytest.approx(16.75)


def test_csv_explicit_schema_and_dates(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("d,v\n2026-01-01,10\n2026-07-31,20\n")
    cat = Catalog()
    cat.register_csv("d", str(p), {"d": T.DATE, "v": T.BIGINT})
    s = presto_tpu.connect(cat)
    assert s.sql("SELECT sum(v) FROM d WHERE d >= DATE '2026-02-01'"
                 ).rows == [(20,)]


def test_jsonl_union_of_keys_and_nested(tmp_path):
    p = tmp_path / "e.jsonl"
    p.write_text('{"a": 1, "b": "x"}\n'
                 '{"a": 2, "c": 2.5, "nested": {"k": [1, 2]}}\n'
                 '{"a": 3, "b": "y", "c": 4.5}\n')
    cat = Catalog()
    cat.register_jsonl("e", str(p))
    s = presto_tpu.connect(cat)
    assert s.sql("SELECT sum(a), count(b), sum(c) FROM e").rows \
        == [(6, 2, 7.0)]
    # nested values surface as JSON text, usable with json functions
    r = s.sql("SELECT json_extract_scalar(nested, '$.k[1]') FROM e "
              "WHERE nested IS NOT NULL").rows
    assert r == [("2",)]


def test_csv_joins_with_other_connectors(tmp_path):
    p = tmp_path / "dim.csv"
    p.write_text("k,label\n1,one\n2,two\n")
    cat = Catalog()
    cat.register_csv("dim", str(p))
    cat.register_memory("f", {"k": T.BIGINT, "v": T.BIGINT},
                        {"k": np.array([1, 2, 2]),
                         "v": np.array([10, 20, 30])})
    s = presto_tpu.connect(cat)
    assert s.sql("SELECT label, sum(v) FROM f, dim WHERE f.k = dim.k "
                 "GROUP BY label ORDER BY label").rows \
        == [("one", 10), ("two", 50)]


def test_jsonl_empty_string_is_not_null(tmp_path):
    """Review regression: "" is a real JSON string, not NULL."""
    p = tmp_path / "s.jsonl"
    p.write_text('{"s": ""}\n{"s": null}\n{"s": "x"}\n')
    cat = Catalog()
    cat.register_jsonl("t", str(p))
    s = presto_tpu.connect(cat)
    assert s.sql("SELECT count(*), count(s) FROM t").rows == [(3, 2)]
    assert s.sql("SELECT count(*) FROM t WHERE s = ''").rows == [(1,)]


def test_csv_inference_falls_back_on_late_strings(tmp_path):
    """Review regression: a non-numeric value past the inference sample
    window downgrades the column to VARCHAR instead of crashing."""
    rows = "\n".join(str(i) for i in range(300))
    p = tmp_path / "late.csv"
    p.write_text("a\n" + rows + "\noops\n")
    cat = Catalog()
    cat.register_csv("t", str(p))
    s = presto_tpu.connect(cat)
    assert cat.get("t").schema["a"] == T.VARCHAR
    assert s.sql("SELECT count(*) FROM t").rows == [(301,)]


def test_csv_explicit_schema_mismatch_is_informative(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("a\nx\n")
    with pytest.raises(ValueError, match="column 'a'"):
        Catalog().register_csv("t", str(p), {"a": T.BIGINT})
