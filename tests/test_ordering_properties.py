"""Ordering-aware execution (ISSUE 3): property derivation, presorted
kernel equivalence, guard-trip fallback, sort-permutation memo, and the
sort-economics counters on the TPC-H plans the tentpole targets."""

import numpy as np
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.batch import Column
from presto_tpu.catalog import Catalog, MemoryTable
from presto_tpu.exec import kernels as K
from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P
from presto_tpu.plan import properties as OP

import jax.numpy as jnp


# ---------------------------------------------------------------------------
# connector ordering declarations hold for the generated data
# ---------------------------------------------------------------------------


def test_tpch_ordering_declarations_match_generated_data():
    from presto_tpu.connectors import tpch as g

    for table, decl in g.ORDERINGS.items():
        data = g.generate(table, 0.01)
        key = None
        for col, asc in decl:
            assert asc, (table, col)
            a = data[col].astype(np.int64)
            span = int(a.max()) - int(a.min()) + 1
            key = a if key is None else key * span + (a - a.min())
        assert np.all(np.diff(key) >= 0), table


@pytest.mark.parametrize("table", [
    "store_sales", "store_returns", "catalog_sales", "catalog_returns",
    "web_sales", "web_returns", "date_dim", "item", "customer",
    "inventory"])
def test_tpcds_ordering_declarations_match_generated_data(table):
    from presto_tpu.connectors import tpcds as g

    decl = g.ORDERINGS[table]
    data = g.generate(table, 0.01)
    key = None
    for col, asc in decl:
        assert asc, (table, col)
        a = data[col].astype(np.int64)
        span = int(a.max()) - int(a.min()) + 1
        key = a if key is None else key * span + (a - a.min())
    assert np.all(np.diff(key) >= 0), table


# ---------------------------------------------------------------------------
# property derivation per node type
# ---------------------------------------------------------------------------


def _cat(order_decl=None, unique=()):
    class Tbl(MemoryTable):
        def ordering(self):
            return list(order_decl or [])

        def unique_keys(self):
            return [tuple(k) for k in unique]

    cat = Catalog()
    cat.register(Tbl("t", {"k": T.BIGINT, "v": T.BIGINT},
                     {"k": np.arange(10), "v": np.arange(10)}))
    return cat


def _scan():
    return P.TableScan("t", {"k$1": "k", "v$2": "v"},
                       {"k$1": T.BIGINT, "v$2": T.BIGINT})


def test_scan_props_seed_and_prefix_cut():
    cat = _cat([("k", True), ("v", True)])
    p = OP.derive(_scan(), cat)
    assert p.sorted_on == (("k$1", True), ("v$2", True))
    assert p.all_live_or_tail
    # ordering column not projected: prefix cuts there
    scan2 = P.TableScan("t", {"v$2": "v"}, {"v$2": T.BIGINT})
    assert OP.derive(scan2, cat).sorted_on == ()
    # unique leading key => every projected symbol FD-of-leading
    cat_u = _cat([("k", True)], unique=[("k",)])
    assert OP.derive(_scan(), cat_u).fd_leading == {"k$1", "v$2"}


def test_filter_preserves_order_but_not_tail():
    cat = _cat([("k", True)])
    f = P.Filter(_scan(), ir.Call("gt", (ir.Ref("v$2", T.BIGINT),
                                         ir.Lit(3, T.BIGINT)), T.BOOLEAN))
    p = OP.derive(f, cat)
    assert p.sorted_on == (("k$1", True),)
    assert not p.all_live_or_tail  # interior holes


def test_project_renames_and_breaks_on_non_ref():
    cat = _cat([("k", True), ("v", True)])
    proj = P.Project(_scan(), {
        "a": ir.Ref("k$1", T.BIGINT),
        "b": ir.Call("add", (ir.Ref("v$2", T.BIGINT),
                             ir.Lit(1, T.BIGINT)), T.BIGINT)})
    p = OP.derive(proj, cat)
    assert p.sorted_on == (("a", True),)  # v$2 not re-exposed as a Ref


def test_aggregate_output_sorted_on_group_keys():
    cat = _cat([("k", True)])
    agg = P.Aggregate(_scan(), ["k$1"],
                      {"c": ir.AggCall("count", (), T.BIGINT)})
    p = OP.derive(agg, cat)
    assert p.sorted_on == (("k$1", True),)
    assert "c" in p.fd_leading  # single-key group output: unique rows


def test_exchange_union_destroy_ordering():
    cat = _cat([("k", True)])
    assert OP.derive(P.Exchange(_scan(), "repartition"), cat).sorted_on == ()
    s1, s2 = _scan(), _scan()
    u = P.Union([s1, s2], ["k$1"], [{"k$1": "k$1"}, {"k$1": "k$1"}])
    assert OP.derive(u, cat).sorted_on == ()


def test_join_preserves_probe_order_and_transfers_fd():
    cat = _cat([("k", True)], unique=[("k",)])
    left = _scan()
    right = P.TableScan("t", {"rk": "k", "rv": "v"},
                        {"rk": T.BIGINT, "rv": T.BIGINT})
    j = P.Join(left, right, "INNER", [("k$1", "rk")])
    j.build_unique = True
    p = OP.derive(j, cat)
    assert p.sorted_on == (("k$1", True),)
    assert not p.all_live_or_tail  # inner join masks interior rows
    assert {"rk", "rv"} <= p.fd_leading  # unique build: constant per key
    assert OP.derive(P.Join(left, right, "FULL", [("k$1", "rk")]),
                     cat).sorted_on == ()


def test_annotate_attaches_guarded_hints():
    cat = _cat([("k", True)], unique=[("k",)])

    class S:
        catalog = cat
        properties = {}

    agg = P.Aggregate(_scan(), ["k$1", "v$2"],
                      {"c": ir.AggCall("count", (), T.BIGINT)})
    plan = P.QueryPlan(P.Output(agg, ["k"], ["k$1"]))
    OP.annotate(plan, S())
    assert agg.ordering_hint == "k$1"
    # v$2 is FD of the unique leading key: static-safe
    assert agg.ordering_hint_safe
    assert agg.ordering_pack_order[0] == "k$1"


# ---------------------------------------------------------------------------
# presorted kernel variants == sort-based kernels
# ---------------------------------------------------------------------------


def _cases():
    rng = np.random.default_rng(7)
    for dtype in (np.int32, np.int64):
        for name, key, sel in [
            ("dups", np.repeat(np.arange(40), rng.integers(1, 9, 40)),
             None),
            ("unique", np.arange(64), None),
            ("masked", np.repeat(np.arange(30), 4),
             rng.random(120) < 0.6),
            ("empty", np.zeros((0,), np.int64), None),
            ("all_masked", np.arange(16), np.zeros(16, bool)),
            ("one_group", np.zeros(50, np.int64), rng.random(50) < 0.8),
        ]:
            key = key.astype(dtype)
            n = len(key)
            sel = np.ones(n, bool) if sel is None else sel
            yield f"{np.dtype(dtype).name}-{name}", key, sel


@pytest.mark.parametrize("case", list(_cases()), ids=lambda c: c[0])
def test_group_ids_presorted_equals_sorted(case):
    _name, key_np, sel_np = case
    sel = jnp.asarray(sel_np)
    key = jnp.where(sel, jnp.asarray(key_np),
                    K.key_sentinel(jnp.asarray(key_np)))
    gid0, rep0, ng0 = K.group_ids(key, sel)
    gid1, newgrp, ng_t, guard = K.group_ids_presorted(key, sel)
    assert not bool(guard)
    ng1 = int(ng_t)
    assert ng1 == ng0
    rep1 = K.nonzero_i32(newgrp, max(ng1, 1), 0)[:ng1] if ng1 \
        else jnp.zeros((0,), jnp.int32)
    assert np.array_equal(np.asarray(gid1), np.asarray(gid0))
    # representatives may be different rows of the same group: compare
    # the represented KEY VALUES
    assert np.array_equal(np.asarray(key)[np.asarray(rep1)],
                          np.asarray(key)[np.asarray(rep0)])


@pytest.mark.parametrize("case", list(_cases()), ids=lambda c: c[0])
def test_group_ids_presorted_static_equals_sorted(case):
    _name, key_np, sel_np = case
    sel = jnp.asarray(sel_np)
    key = jnp.where(sel, jnp.asarray(key_np),
                    K.key_sentinel(jnp.asarray(key_np)))
    for cap in (4, 64):
        gid0, rep0, ex0, ov0 = K.group_ids_static(key, cap)
        gid1, rep1, ex1, ov1, guard = K.group_ids_presorted_static(key, cap)
        assert not bool(guard)
        assert bool(ov1) == bool(ov0)
        if bool(ov0):
            continue  # overflowed: caller re-runs dynamically anyway
        assert np.array_equal(np.asarray(gid1), np.asarray(gid0))
        assert np.array_equal(np.asarray(ex1), np.asarray(ex0))
        if len(key_np) == 0:
            continue  # rep indices have no rows to represent
        live = np.asarray(ex0)
        assert np.array_equal(
            np.asarray(key)[np.asarray(rep1)][live],
            np.asarray(key)[np.asarray(rep0)][live])


def test_group_ids_presorted_guard_trips_on_unsorted():
    key = jnp.asarray(np.array([3, 1, 2, 0], np.int64))
    sel = jnp.ones(4, bool)
    *_rest, guard = K.group_ids_presorted(key, sel)
    assert bool(guard)
    *_rest, ov, guard_s = K.group_ids_presorted_static(key, 8)
    assert bool(guard_s)
    # masked rows may sit anywhere without tripping the LIVE-run guard
    key2 = jnp.where(jnp.asarray([True, False, True, True]),
                     jnp.asarray([1, 99, 1, 2], dtype=jnp.int64),
                     K.key_sentinel(jnp.asarray([0], jnp.int64)))
    *_rest, g2 = K.group_ids_presorted(key2,
                                       jnp.asarray([True, False, True, True]))
    assert not bool(g2)


def test_monotone_guard():
    assert not bool(K.monotone_guard(jnp.asarray([1, 1, 2, 9])))
    assert bool(K.monotone_guard(jnp.asarray([1, 3, 2])))
    assert not bool(K.monotone_guard(jnp.asarray([], dtype=jnp.int64)))


def test_build_probe_identity_order_on_sorted_build():
    rng = np.random.default_rng(3)
    build = np.sort(rng.integers(0, 50, 80)).astype(np.int64)
    probe = rng.integers(-5, 60, 200).astype(np.int64)
    o0, lb0, ub0 = K.build_probe(jnp.asarray(build), jnp.asarray(probe))
    ident = jnp.arange(len(build), dtype=jnp.int32)
    o1, lb1, ub1 = K.build_probe(jnp.asarray(build), jnp.asarray(probe),
                                 build_order=ident)
    assert np.array_equal(np.asarray(lb0), np.asarray(lb1))
    assert np.array_equal(np.asarray(ub0), np.asarray(ub1))
    # matched build-key multisets agree per probe row
    b0, b1 = np.asarray(o0), np.asarray(o1)
    for i in rng.integers(0, 200, 20):
        assert sorted(build[b0[lb0[i]:ub0[i]]]) \
            == sorted(build[b1[lb1[i]:ub1[i]]])


# ---------------------------------------------------------------------------
# end-to-end: exploitation, guard-trip fallback, memo, counters
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tpch_session(tpch_catalog_tiny):
    return presto_tpu.connect(tpch_catalog_tiny)


def test_sorts_elided_on_q3_q18(tpch_session):
    """ISSUE-3 acceptance: QueryStats.sorts_elided > 0 on TPC-H q3/q18."""
    from tests.tpch_queries import QUERIES

    s = tpch_session
    for qid in (3, 18):
        st = s.sql(QUERIES[qid]).stats
        assert st.sorts_elided > 0, (qid, vars(st))
        assert st.ordering_guard_trips == 0, (qid, vars(st))


def test_sort_memo_hit_counts_on_q1_q3_q18(tpch_session):
    """Measured memo economics of the three target plans: q18's two
    transitive-semi probes of the shared HAVING subquery ride ONE build
    sort (1 hit); q1 (direct sort-free grouping + elided ORDER BY) and
    q3 (index joins + presorted grouping) leave nothing to memoize."""
    from tests.tpch_queries import QUERIES

    s = tpch_session
    expect = {1: 0, 3: 0, 18: 1}
    for qid, hits in expect.items():
        st = s.sql(QUERIES[qid]).stats
        assert st.sort_memo_hits == hits, (qid, vars(st))


def test_group_then_order_by_elides_sort(tpch_session):
    """Grouped output is CERTAINLY sorted on its group keys (runtime
    channel), so GROUP BY k ORDER BY k skips the ORDER BY sort —
    and still returns correctly ordered rows."""
    s = tpch_session
    q = ("SELECT l_orderkey, count(*) c FROM lineitem "
         "GROUP BY l_orderkey ORDER BY l_orderkey")
    r = s.sql(q)
    keys = [row[0] for row in r.rows]
    assert keys == sorted(keys)
    assert r.stats.sorts_elided > 0, vars(r.stats)


def _lying_catalog(n=5000, seed=11):
    """A memory table whose connector LIES about being sorted on k."""
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 97, n)  # deliberately unsorted
    v = rng.integers(0, 1000, n)

    class LyingTable(MemoryTable):
        def ordering(self):
            return [("k", True)]

    cat = Catalog()
    cat.register(LyingTable("liar", {"k": T.BIGINT, "v": T.BIGINT},
                            {"k": k, "v": v}))
    return cat, k, v


@pytest.mark.parametrize("mode", ["auto", "dynamic"])
def test_misdeclared_ordering_falls_back_identically(mode):
    """ISSUE-3 acceptance: a mis-declared connector ordering produces
    results identical to the honest path — the monotonicity guard trips
    (host-checked in dynamic mode; via the static guard channel in
    compiled mode, which re-runs dynamically) and the sort path runs."""
    cat, k, v = _lying_catalog()
    s = presto_tpu.connect(cat)
    s.properties["execution_mode"] = mode
    q = "SELECT k, count(*) c, sum(v) sv FROM liar GROUP BY k ORDER BY k"
    r = s.sql(q)
    import collections

    cnt = collections.Counter(k.tolist())
    sv = collections.defaultdict(int)
    for ki, vi in zip(k.tolist(), v.tolist()):
        sv[ki] += vi
    want = [(ki, cnt[ki], sv[ki]) for ki in sorted(cnt)]
    assert r.rows == want
    # the same query again (compiled mode caches the DYNAMIC verdict)
    assert s.sql(q).rows == want
    if mode == "dynamic":
        assert s.last_stats.ordering_guard_trips >= 1, vars(s.last_stats)


def test_misdeclared_ordering_as_join_build_falls_back():
    """The presorted JOIN build claim is guard-verified the same way."""
    cat, k, v = _lying_catalog(n=900, seed=5)
    rng = np.random.default_rng(6)
    cat.register(MemoryTable(
        "probe", {"pk": T.BIGINT, "w": T.BIGINT},
        {"pk": rng.integers(0, 97, 400), "w": np.arange(400)}))
    s = presto_tpu.connect(cat)
    s.properties["execution_mode"] = "dynamic"
    q = ("SELECT count(*) c FROM probe, liar WHERE pk = k")
    r = s.sql(q)
    import collections

    cnt = collections.Counter(k.tolist())
    pk = np.asarray(cat.get("probe").data["pk"])
    want = int(sum(cnt.get(int(x), 0) for x in pk))
    assert r.rows == [(want,)]


def test_memo_hits_on_repeated_group_by_same_key():
    """Two subqueries grouping the same scan column sort its packed key
    ONCE: the second grouping replays the memoized permutation, and the
    join of the two grouped outputs (both certainly sorted on k) elides
    its build argsort."""
    rng = np.random.default_rng(2)
    n = 4000
    cat = Catalog()
    cat.register(MemoryTable(
        "t", {"k": T.BIGINT, "v": T.BIGINT},
        {"k": rng.integers(0, 500, n), "v": rng.integers(0, 9, n)}))
    s = presto_tpu.connect(cat)
    s.properties["execution_mode"] = "dynamic"
    q = ("SELECT a.k, a.s, b.c FROM "
         "(SELECT k, sum(v) s FROM t GROUP BY k) a, "
         "(SELECT k, count(*) c FROM t GROUP BY k) b "
         "WHERE a.k = b.k")
    r = s.sql(q)
    assert len(r.rows) == len(set(np.asarray(cat.get("t").data["k"]).tolist()))
    st = s.last_stats
    assert st.sort_memo_hits >= 1, vars(st)
    assert st.sorts_elided >= 1, vars(st)


def test_ordering_aware_execution_can_be_disabled(tpch_session):
    from tests.tpch_queries import QUERIES

    s = presto_tpu.connect(tpch_session.catalog)
    s.properties["ordering_aware_execution"] = False
    from tests.sqlite_oracle import normalize

    base = tpch_session.sql(QUERIES[3]).rows
    off = s.sql(QUERIES[3]).rows
    assert normalize(base) == normalize(off)
    assert s.last_stats.sorts_elided == 0, vars(s.last_stats)
