"""Round-5 scalar batch: volatile functions, inverse CDFs, color
functions, string/array/map long tail.

Reference: presto-main/.../operator/scalar/ — MathFunctions (random,
inverse*Cdf, cosineSimilarity), UuidFunction, ColorFunctions,
StringFunctions (splitToMap/splitToMultimap/strrpos), WordStemFunction,
KeySamplingPercentFunction, ArrayFunctions + MapFunctions long tail,
and the volatile-query cache semantics (a cached compiled program must
not freeze now()/random() — exec/executor._volatile_nonce).
"""

import math
import time

import pytest

import presto_tpu
from presto_tpu.catalog import Catalog


@pytest.fixture(scope="module")
def s():
    return presto_tpu.connect(Catalog())


def one(s, sql):
    rows = s.sql(sql).rows
    assert len(rows) == 1
    return rows[0][0] if len(rows[0]) == 1 else rows[0]


# ---------------------------------------------------------------------
# volatile functions + cache-freshness semantics
# ---------------------------------------------------------------------

def test_now_is_fresh_across_executions_of_same_text(s):
    """Regression: the compiled-plan cache used to bake the first
    execution's instant into the program, so a re-run of the SAME query
    text returned a stale now()."""
    a = one(s, "SELECT now()")
    time.sleep(0.01)
    b = one(s, "SELECT now()")
    assert a != b


def test_random_per_row_and_per_execution(s):
    q = "SELECT random() FROM (VALUES (1),(2),(3),(4)) AS t(x)"
    r1 = [v[0] for v in s.sql(q).rows]
    assert len(set(r1)) == 4  # per-row, not one value broadcast
    assert all(0.0 <= v < 1.0 for v in r1)
    r2 = [v[0] for v in s.sql(q).rows]
    assert r1 != r2  # per-execution fresh despite identical text


def test_random_bounded(s):
    vals = [one(s, "SELECT random(10)") for _ in range(8)]
    assert all(0 <= v < 10 for v in vals)
    # result type follows the bound's type (reference: random(n) is
    # typed per overload)
    assert one(s, "SELECT typeof(random(10))").lower() in (
        "integer", "bigint")


def test_rand_alias(s):
    assert 0.0 <= one(s, "SELECT rand()") < 1.0


def test_uuid_shape_and_uniqueness(s):
    rows = s.sql("SELECT uuid() FROM (VALUES (1),(2),(3)) AS t(x)").rows
    vals = [r[0] for r in rows]
    assert len(set(vals)) == 3
    for v in vals:
        assert len(v) == 36 and v.count("-") == 4


def test_shuffle_is_a_permutation(s):
    v = one(s, "SELECT shuffle(ARRAY[1,2,3,4,5,6,7,8])")
    assert sorted(v) == [1, 2, 3, 4, 5, 6, 7, 8]


# ---------------------------------------------------------------------
# inverse CDFs — round-trip against the engine's own forward CDFs plus
# externally-known constants
# ---------------------------------------------------------------------

@pytest.mark.parametrize("fwd,inv,args,p", [
    ("beta_cdf(2.0, 5.0, {v})", "inverse_beta_cdf(2.0, 5.0, {p})", (), 0.3),
    ("chi_squared_cdf(3.0, {v})", "inverse_chi_squared_cdf(3.0, {p})",
     (), 0.95),
    ("gamma_cdf(2.0, 2.0, {v})", "inverse_gamma_cdf(2.0, 2.0, {p})",
     (), 0.5),
    ("f_cdf(5.0, 2.0, {v})", "inverse_f_cdf(5.0, 2.0, {p})", (), 0.7),
    ("laplace_cdf(1.0, 2.0, {v})", "inverse_laplace_cdf(1.0, 2.0, {p})",
     (), 0.25),
    ("logistic_cdf(0.0, 1.0, {v})", "inverse_logistic_cdf(0.0, 1.0, {p})",
     (), 0.75),
    ("weibull_cdf(1.5, 1.0, {v})", "inverse_weibull_cdf(1.5, 1.0, {p})",
     (), 0.5),
])
def test_inverse_cdf_round_trip(s, fwd, inv, args, p):
    v = one(s, f"SELECT {inv.format(p=p)}")
    back = one(s, f"SELECT {fwd.format(v=v)}")
    assert back == pytest.approx(p, abs=1e-6)


def test_inverse_cdf_known_values(s):
    # chi^2(df=3) 95th percentile = 7.8147 (standard table value)
    assert one(s, "SELECT inverse_chi_squared_cdf(3.0, 0.95)") == \
        pytest.approx(7.8147, abs=1e-3)
    # logistic closed form: mu + s*ln(p/(1-p))
    assert one(s, "SELECT inverse_logistic_cdf(0.0, 1.0, 0.75)") == \
        pytest.approx(math.log(3.0), abs=1e-9)
    assert one(s, "SELECT inverse_laplace_cdf(0.0, 1.0, 0.25)") == \
        pytest.approx(-math.log(2.0), abs=1e-9)


def test_inverse_discrete_cdfs(s):
    assert one(s, "SELECT inverse_poisson_cdf(3.0, 0.5)") == 3
    assert one(s, "SELECT inverse_binomial_cdf(20, 0.5, 0.5)") == 10
    # smallest k with CDF >= p, CDF(k) must reach p and CDF(k-1) must not
    k = one(s, "SELECT inverse_poisson_cdf(10.0, 0.9)")
    hi = one(s, f"SELECT poisson_cdf(10.0, {k})")
    lo = one(s, f"SELECT poisson_cdf(10.0, {k - 1})")
    assert lo < 0.9 <= hi


def test_inverse_cdf_out_of_range_p_is_null(s):
    assert s.sql("SELECT inverse_beta_cdf(2.0, 5.0, 1.5)").rows[0][0] \
        is None or math.isnan(
            s.sql("SELECT inverse_beta_cdf(2.0, 5.0, 1.5)").rows[0][0])


def test_cosine_similarity(s):
    assert one(
        s, "SELECT cosine_similarity(MAP(ARRAY['a','b'], ARRAY[1.0,2.0]),"
        " MAP(ARRAY['a','b'], ARRAY[2.0,4.0]))") == pytest.approx(1.0)
    assert one(
        s, "SELECT cosine_similarity(MAP(ARRAY['a'], ARRAY[1.0]),"
        " MAP(ARRAY['b'], ARRAY[1.0]))") == pytest.approx(0.0)


# ---------------------------------------------------------------------
# string long tail
# ---------------------------------------------------------------------

def test_strrpos(s):
    assert one(s, "SELECT strrpos('abcabc', 'b')") == 5
    assert one(s, "SELECT strrpos('abcabc', 'b', 2)") == 2
    assert one(s, "SELECT strrpos('abc', 'z')") == 0


def test_split_to_map(s):
    assert one(s, "SELECT split_to_map('a=1,b=2', ',', '=')") == \
        (("a", "1"), ("b", "2"))
    assert one(s, "SELECT split_to_multimap('a=1,a=2,b=3', ',', '=')") == \
        (("a", ("1", "2")), ("b", ("3",)))
    # duplicate keys are an error for the map form -> NULL entry here
    assert s.sql("SELECT split_to_map('a=1,a=2', ',', '=')").rows[0][0] \
        is None


def test_word_stem_porter(s):
    cases = {"running": "run", "ponies": "poni", "caresses": "caress",
             "relational": "relat", "hopeful": "hope", "sky": "sky"}
    for w, st in cases.items():
        assert one(s, f"SELECT word_stem('{w}')") == st
    # over a column (dictionary path)
    rows = s.sql("SELECT word_stem(x) FROM "
                 "(VALUES ('flies'),('denied')) AS t(x)").rows
    assert [r[0] for r in rows] == ["fli", "deni"]


def test_key_sampling_percent(s):
    v = one(s, "SELECT key_sampling_percent('some_key')")
    assert 0.0 <= v < 1.0
    assert v == one(s, "SELECT key_sampling_percent('some_key')")


# ---------------------------------------------------------------------
# color functions
# ---------------------------------------------------------------------

def test_color_codes(s):
    assert one(s, "SELECT color('red')") == -2
    assert one(s, "SELECT color('#f00')") == 0xFF0000
    assert one(s, "SELECT rgb(16, 32, 48)") == (16 << 16) | (32 << 8) | 48


def test_render_and_bar(s):
    assert one(s, "SELECT render('hi', color('red'))") == \
        "\x1b[31mhi\x1b[0m"
    assert one(s, "SELECT render(true)") == "\x1b[32m✔\x1b[0m"
    assert one(s, "SELECT render(false)") == "\x1b[31m✘\x1b[0m"
    b = one(s, "SELECT bar(0.5, 10)")
    assert b.count("█") == 5 and b.endswith("\x1b[0m" + " " * 5)


# ---------------------------------------------------------------------
# array long tail
# ---------------------------------------------------------------------

def test_array_frequency(s):
    assert one(s, "SELECT array_frequency(ARRAY[1,1,2,NULL])") == \
        ((1, 2), (2, 1))


def test_array_cum_sum(s):
    assert one(s, "SELECT array_cum_sum(ARRAY[1,2,3])") == (1, 3, 6)
    assert one(s, "SELECT array_cum_sum(ARRAY[1.5, 2.5])") == (1.5, 4.0)
    assert one(s, "SELECT array_cum_sum(ARRAY[1, NULL, 2])") == \
        (1, None, None)


def test_array_normalize(s):
    assert one(s, "SELECT array_normalize(ARRAY[3.0, 4.0], 2)") == \
        pytest.approx((0.6, 0.8))
    assert one(s, "SELECT array_normalize(ARRAY[0.0, 0.0], 2)") == \
        (0.0, 0.0)


def test_array_sort_desc(s):
    assert one(s, "SELECT array_sort_desc(ARRAY[1,3,2])") == (3, 2, 1)
    assert one(s, "SELECT array_sort_desc(ARRAY[1, NULL, 2])") == \
        (2, 1, None)


def test_combinations_and_ngrams(s):
    assert one(s, "SELECT combinations(ARRAY[1,2,3], 2)") == \
        ((1, 2), (1, 3), (2, 3))
    assert one(s, "SELECT ngrams(ARRAY['a','b','c'], 2)") == \
        (("a", "b"), ("b", "c"))
    assert one(s, "SELECT ngrams(ARRAY['a'], 3)") == (("a",),)


def test_zip_pads_with_null(s):
    assert one(s, "SELECT zip(ARRAY[1,2], ARRAY['a','b','c'])") == \
        ((1, "a"), (2, "b"), (None, "c"))


# ---------------------------------------------------------------------
# map long tail
# ---------------------------------------------------------------------

def test_map_remove_null_values(s):
    assert one(s, "SELECT map_remove_null_values("
               "MAP(ARRAY['a','b'], ARRAY[1, NULL]))") == (("a", 1),)


def test_map_normalize(s):
    assert one(s, "SELECT map_normalize("
               "MAP(ARRAY['a','b'], ARRAY[1.0, 3.0]))") == \
        (("a", 0.25), ("b", 0.75))


def test_map_subset(s):
    assert one(s, "SELECT map_subset(MAP(ARRAY['a','b'], ARRAY[1,2]), "
               "ARRAY['a','c'])") == (("a", 1),)


def test_multimap_from_entries(s):
    assert one(s, "SELECT multimap_from_entries("
               "ARRAY[ROW('a',1), ROW('a',2), ROW('b',3)])") == \
        (("a", (1, 2)), ("b", (3,)))


def test_map_zip_with(s):
    assert one(s, "SELECT map_zip_with("
               "MAP(ARRAY['a','b'], ARRAY[1,2]), "
               "MAP(ARRAY['b','c'], ARRAY[10,20]), "
               "(k, v1, v2) -> coalesce(v1,0) + coalesce(v2,0))") == \
        (("a", 1), ("b", 12), ("c", 20))


def test_keys_values_match_family(s):
    assert one(s, "SELECT all_keys_match(MAP(ARRAY['a','ab'], "
               "ARRAY[1,2]), k -> length(k) >= 1)") is True
    assert one(s, "SELECT any_keys_match(MAP(ARRAY['a'], ARRAY[1]), "
               "k -> k = 'z')") is False
    assert one(s, "SELECT no_keys_match(MAP(ARRAY['a'], ARRAY[1]), "
               "k -> k = 'z')") is True
    assert one(s, "SELECT any_values_match(MAP(ARRAY['a','b'], "
               "ARRAY[1,2]), v -> v > 1)") is True
    assert one(s, "SELECT no_values_match(MAP(ARRAY['a'], ARRAY[1]), "
               "v -> v > 5)") is True


def test_match_family_null_three_valued(s):
    # no TRUE, one NULL -> NULL (the reference's three-valued quantifier)
    assert s.sql("SELECT any_values_match(MAP(ARRAY['a','b'], "
                 "ARRAY[1, NULL]), v -> v > 5)").rows[0][0] is None
    assert s.sql("SELECT all_keys_match(MAP(ARRAY['a'], ARRAY[1]), "
                 "k -> k > 'z')").rows[0][0] is False


# ---- comparator/lambda overloads + data size (second batch) ----------

def test_array_sort_nulls_last(s):
    assert one(s, "SELECT array_sort(ARRAY[3, 1, NULL, 2])") == \
        (1, 2, 3, None)


def test_array_sort_comparator(s):
    assert one(s, "SELECT array_sort(ARRAY[3, 2, 5, 1, 2], "
               "(x, y) -> y - x)") == (5, 3, 2, 2, 1)
    assert one(s, "SELECT array_sort(ARRAY['a', 'ccc', 'bb'], (x, y) -> "
               "IF(length(x) < length(y), -1, "
               "IF(length(x) > length(y), 1, 0)))") == ("a", "bb", "ccc")


def test_regexp_replace_lambda(s):
    assert one(s, "SELECT regexp_replace('new york', '(\\w)(\\w*)', "
               "x -> upper(x[1]) || lower(x[2]))") == "New York"


def test_parse_presto_data_size(s):
    assert one(s, "SELECT parse_presto_data_size('1kB')") == 1024
    assert one(s, "SELECT parse_presto_data_size('2.5GB')") == \
        int(2.5 * (1 << 30))


def test_array_join_null_replacement(s):
    assert one(s, "SELECT array_join(ARRAY[1, NULL, 2], ',')") == "1,2"
    assert one(s, "SELECT array_join(ARRAY[1, NULL, 2], ',', 'N/A')") == \
        "1,N/A,2"


# ---- collection ordering + IS DISTINCT FROM --------------------------

def test_array_row_ordering_is_lexicographic(s):
    """Regression: </<=/>/>= over ARRAY/ROW used to compare dictionary
    CODES (canonical-repr order), so ARRAY[2] < ARRAY[10] was false."""
    assert one(s, "SELECT ARRAY[1,2] < ARRAY[1,3]") is True
    assert one(s, "SELECT ARRAY[2] < ARRAY[10]") is True
    assert one(s, "SELECT ARRAY[1,2] > ARRAY[1]") is True  # prefix
    assert one(s, "SELECT ROW(1,'a') < ROW(2,'a')") is True
    assert one(s, "SELECT ROW(1,'b') >= ROW(1,'a')") is True


def test_is_distinct_from(s):
    assert one(s, "SELECT 1 IS DISTINCT FROM NULL") is True
    assert one(s, "SELECT NULL IS NOT DISTINCT FROM NULL") is True
    assert one(s, "SELECT 1 IS DISTINCT FROM 1") is False
    assert one(s, "SELECT 'a' IS NOT DISTINCT FROM 'a'") is True
    rows = s.sql("SELECT x IS DISTINCT FROM y FROM (VALUES (1, 1), "
                 "(1, NULL), (CAST(NULL AS INTEGER), NULL)) "
                 "AS t(x, y)").rows
    assert [r[0] for r in rows] == [False, True, False]
    # never NULL, usable directly in WHERE
    assert one(s, "SELECT count(*) FROM (VALUES (1),(2)) t(x) "
               "WHERE x IS DISTINCT FROM 1") == 1
