"""Chunked (grouped) execution: stream the bucketed big tables
chunk-by-chunk (exec/chunked.py) and match whole-table results.

Reference: grouped execution (Lifespan bucket-at-a-time,
execution/Lifespan.java:26-38) + partial/final split (AddExchanges)."""

import pytest

import presto_tpu
from presto_tpu.catalog import tpch_catalog

from tpch_queries import QUERIES

SF = 0.05


@pytest.fixture(scope="module")
def sessions():
    chunked = presto_tpu.connect(
        tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    chunked.properties["chunked_rows_threshold"] = 50_000
    chunked.properties["chunk_orders"] = 20_000  # ~4 chunks
    whole = presto_tpu.connect(
        tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    return chunked, whole


def norm(rows):
    return [tuple(round(v, 2) if isinstance(v, float) else v for v in r)
            for r in rows]


# queries covering: sort-free agg (1), global agg (6), colocated join +
# partial topN (3), double lineitem scan + semi join + group on orderkey
# (18), resident multi-join + partial/final agg + LIKE pushdown (9),
# agg-on-agg (13 falls back: o_comment), distinct agg (16 falls back)
@pytest.mark.parametrize("qid", [1, 3, 6, 9, 12, 14, 18])
def test_chunked_matches_whole(sessions, qid):
    chunked, whole = sessions
    got = chunked.sql(QUERIES[qid])
    want = whole.sql(QUERIES[qid])
    assert norm(got.rows) == norm(want.rows)


def test_chunked_mode_actually_used(sessions):
    chunked, _ = sessions
    from presto_tpu.exec import chunked as CH
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.sql.parser import parse

    stmt = parse(QUERIES[3])
    plan = plan_statement(chunked, stmt)
    assert CH.chunk_plan_needed(chunked, plan)
    r = CH.run_chunked(chunked, stmt, QUERIES[3])
    assert len(r.rows) == 10


def test_like_pushdown_into_scan(sessions):
    """p_name LIKE '%green%' becomes a connector-computed virtual
    column (no p_name materialization)."""
    chunked, _ = sessions
    text = chunked.sql("EXPLAIN " + QUERIES[9]).rows[0][0]
    assert "p_name$contains$green" in text


def test_chunked_mesh_composition(sessions):
    """Chunk loop x device mesh: each superstep runs 4 bucket-aligned
    micro-chunks under shard_map on the virtual CPU mesh (VERDICT r2
    item 5 — HBM-exceeding queries must not be single-chip by
    construction).  Results must match the single-device chunk loop."""
    import presto_tpu
    from presto_tpu.catalog import tpch_catalog

    meshed = presto_tpu.connect(
        tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    meshed.properties["chunked_rows_threshold"] = 50_000
    meshed.properties["chunk_orders"] = 5_000  # ~15 micro-chunks
    meshed.properties["chunk_mesh_devices"] = 4
    _, whole = sessions
    for qid in (1, 3, 18):
        got = meshed.sql(QUERIES[qid])
        want = whole.sql(QUERIES[qid])
        assert norm(got.rows) == norm(want.rows), qid


def test_chunked_mesh_actually_chunkloops(sessions):
    from presto_tpu.exec import chunked as CH
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.sql.parser import parse
    import presto_tpu
    from presto_tpu.catalog import tpch_catalog

    meshed = presto_tpu.connect(
        tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    meshed.properties["chunked_rows_threshold"] = 50_000
    meshed.properties["chunk_orders"] = 5_000
    meshed.properties["chunk_mesh_devices"] = 4
    stmt = parse(QUERIES[3])
    plan = plan_statement(meshed, stmt)
    assert CH.chunk_plan_needed(meshed, plan)
    r = CH.run_chunked(meshed, stmt, QUERIES[3])
    assert len(r.rows) == 10
    runner = next(iter(meshed._chunked_cache.values()))[2]
    assert any(isinstance(k, tuple) and k and k[0] == "mesh"
               for k in runner._jit), "mesh superstep path not taken"


# standard Q18's HAVING > 300 is EMPTY at this SF (vacuous assertions);
# this variant keeps ~2/3 of the orders so lineitem-grain fragments and
# large exchanges are really exercised
Q18_LOW = QUERIES[18].replace("sum(l_quantity) > 300",
                              "sum(l_quantity) > 100")


@pytest.mark.parametrize("chunk_orders", [1_000, 3_000, 5_000, 20_000])
@pytest.mark.parametrize("mesh_n", [1, 4, 8])
def test_chunk_size_mesh_sweep(sessions, chunk_orders, mesh_n):
    """Round-3 VERDICT item 2: the chunk-capacity heuristic must hold at
    EVERY chunk size x mesh width, not just the sizes the other tests
    happen to pick (the round-3 dryrun tripped the old family-wide
    bound at chunk_orders=3000 on Q18's lineitem-grain fragment).  A
    bound miss must degrade (grow + retry), never raise Unchunkable."""
    _, whole = sessions
    s = presto_tpu.connect(tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    s.properties["chunked_rows_threshold"] = 50_000
    s.properties["chunk_orders"] = chunk_orders
    s.properties["chunk_mesh_devices"] = mesh_n
    from presto_tpu.exec import chunked as CH
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.sql.parser import parse

    for sql in (QUERIES[3], Q18_LOW):
        stmt = parse(sql)
        plan = plan_statement(s, stmt)
        assert CH.chunk_plan_needed(s, plan)
        # straight through the chunked runner: no silent whole-table
        # fallback can mask an Unchunkable here
        got = CH.run_chunked(s, stmt, sql)
        want = whole.sql(sql).rows
        assert want, "vacuously-empty oracle"
        assert norm(got.rows) == norm(want), (sql[:40], chunk_orders,
                                              mesh_n)


def test_bounded_accumulator_pipelined_loop(sessions):
    """When fixed-cap buffering of all chunks would exceed
    chunk_buffer_max_rows, the pipelined loop folds chunks into a
    bounded on-device accumulator instead of dropping to the per-chunk
    syncing loop (round-3 VERDICT item 4).  Results must match."""
    _, whole = sessions
    s = presto_tpu.connect(tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    s.properties["chunked_rows_threshold"] = 50_000
    s.properties["chunk_orders"] = 5_000   # ~15 chunks
    # small budget: cap * nchunks exceeds it, actual live rows do not
    s.properties["chunk_buffer_max_rows"] = 50_000
    from presto_tpu.exec import chunked as CH
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.sql.parser import parse

    acc_calls = {"hit": 0}
    orig = CH._FragmentRunner._chunk_loop_accumulate

    def spy(self, *a, **k):
        r = orig(self, *a, **k)
        if r is not None:
            acc_calls["hit"] += 1
        return r

    monkeypatch = pytest.MonkeyPatch()
    monkeypatch.setattr(CH._FragmentRunner, "_chunk_loop_accumulate", spy)
    try:
        # unbounded root (no LIMIT) + orderkey-skewed filter: chunk 0
        # calibrates a large cap, later chunks are sparse — the exact
        # shape fixed-cap buffering wastes HBM on
        group_q = ("SELECT l_orderkey, sum(l_quantity) q FROM lineitem "
                   "WHERE l_orderkey < 60000 GROUP BY l_orderkey "
                   "HAVING sum(l_quantity) > 50")
        for sql in (group_q,):
            stmt = parse(sql)
            assert CH.chunk_plan_needed(s, plan_statement(s, stmt))
            got = CH.run_chunked(s, stmt, sql)
            want = whole.sql(sql).rows
            assert want, "vacuously-empty oracle"
            assert norm(got.rows) == norm(want), sql[:40]
        assert acc_calls["hit"] >= 1, \
            "bounded accumulator path never engaged"
    finally:
        monkeypatch.undo()
