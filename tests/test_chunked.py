"""Chunked (grouped) execution: stream the bucketed big tables
chunk-by-chunk (exec/chunked.py) and match whole-table results.

Reference: grouped execution (Lifespan bucket-at-a-time,
execution/Lifespan.java:26-38) + partial/final split (AddExchanges)."""

import pytest

import presto_tpu
from presto_tpu.catalog import tpch_catalog

from tpch_queries import QUERIES

SF = 0.05


@pytest.fixture(scope="module")
def sessions():
    chunked = presto_tpu.connect(
        tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    chunked.properties["chunked_rows_threshold"] = 50_000
    chunked.properties["chunk_orders"] = 20_000  # ~4 chunks
    whole = presto_tpu.connect(
        tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    return chunked, whole


def norm(rows):
    return [tuple(round(v, 2) if isinstance(v, float) else v for v in r)
            for r in rows]


# queries covering: sort-free agg (1), global agg (6), colocated join +
# partial topN (3), double lineitem scan + semi join + group on orderkey
# (18), resident multi-join + partial/final agg + LIKE pushdown (9),
# agg-on-agg (13 falls back: o_comment), distinct agg (16 falls back)
@pytest.mark.parametrize("qid", [1, 3, 6, 9, 12, 14, 18])
def test_chunked_matches_whole(sessions, qid):
    chunked, whole = sessions
    got = chunked.sql(QUERIES[qid])
    want = whole.sql(QUERIES[qid])
    assert norm(got.rows) == norm(want.rows)


def test_chunked_mode_actually_used(sessions):
    chunked, _ = sessions
    from presto_tpu.exec import chunked as CH
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.sql.parser import parse

    stmt = parse(QUERIES[3])
    plan = plan_statement(chunked, stmt)
    assert CH.chunk_plan_needed(chunked, plan)
    r = CH.run_chunked(chunked, stmt, QUERIES[3])
    assert len(r.rows) == 10


def test_like_pushdown_into_scan(sessions):
    """p_name LIKE '%green%' becomes a connector-computed virtual
    column (no p_name materialization)."""
    chunked, _ = sessions
    text = chunked.sql("EXPLAIN " + QUERIES[9]).rows[0][0]
    assert "p_name$contains$green" in text


def test_chunked_mesh_composition(sessions):
    """Chunk loop x device mesh: each superstep runs 4 bucket-aligned
    micro-chunks under shard_map on the virtual CPU mesh (VERDICT r2
    item 5 — HBM-exceeding queries must not be single-chip by
    construction).  Results must match the single-device chunk loop."""
    import presto_tpu
    from presto_tpu.catalog import tpch_catalog

    meshed = presto_tpu.connect(
        tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    meshed.properties["chunked_rows_threshold"] = 50_000
    meshed.properties["chunk_orders"] = 5_000  # ~15 micro-chunks
    meshed.properties["chunk_mesh_devices"] = 4
    _, whole = sessions
    for qid in (1, 3, 18):
        got = meshed.sql(QUERIES[qid])
        want = whole.sql(QUERIES[qid])
        assert norm(got.rows) == norm(want.rows), qid


def test_chunked_mesh_actually_chunkloops(sessions):
    from presto_tpu.exec import chunked as CH
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.sql.parser import parse
    import presto_tpu
    from presto_tpu.catalog import tpch_catalog

    meshed = presto_tpu.connect(
        tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    meshed.properties["chunked_rows_threshold"] = 50_000
    meshed.properties["chunk_orders"] = 5_000
    meshed.properties["chunk_mesh_devices"] = 4
    stmt = parse(QUERIES[3])
    plan = plan_statement(meshed, stmt)
    assert CH.chunk_plan_needed(meshed, plan)
    r = CH.run_chunked(meshed, stmt, QUERIES[3])
    assert len(r.rows) == 10
    runner = next(iter(meshed._chunked_cache.values()))[2]
    assert any(isinstance(k, tuple) and k and k[0] == "mesh"
               for k in runner._jit), "mesh superstep path not taken"


# standard Q18's HAVING > 300 is EMPTY at this SF (vacuous assertions);
# this variant keeps ~2/3 of the orders so lineitem-grain fragments and
# large exchanges are really exercised
Q18_LOW = QUERIES[18].replace("sum(l_quantity) > 300",
                              "sum(l_quantity) > 100")


# the interior sweep points ride tier 2 as well: 1_000 and 20_000
# bracket the chunk-capacity heuristic's extremes in tier 1
@pytest.mark.parametrize("chunk_orders", [
    1_000,
    pytest.param(3_000, marks=pytest.mark.slow),
    pytest.param(5_000, marks=pytest.mark.slow),
    20_000,
])
# the meshed sweep points are tier-2 (slow): each compiles a fresh
# shard_map program per chunk size (~10s each on the CPU mesh) and
# mesh-path correctness is already tier-1 via
# test_chunked_mesh_composition; the mesh_n=1 sweep keeps the
# chunk-capacity heuristic covered at every size
@pytest.mark.parametrize("mesh_n", [
    1,
    pytest.param(4, marks=pytest.mark.slow),
    pytest.param(8, marks=pytest.mark.slow),
])
def test_chunk_size_mesh_sweep(sessions, chunk_orders, mesh_n):
    """Round-3 VERDICT item 2: the chunk-capacity heuristic must hold at
    EVERY chunk size x mesh width, not just the sizes the other tests
    happen to pick (the round-3 dryrun tripped the old family-wide
    bound at chunk_orders=3000 on Q18's lineitem-grain fragment).  A
    bound miss must degrade (grow + retry), never raise Unchunkable."""
    _, whole = sessions
    s = presto_tpu.connect(tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    s.properties["chunked_rows_threshold"] = 50_000
    s.properties["chunk_orders"] = chunk_orders
    s.properties["chunk_mesh_devices"] = mesh_n
    from presto_tpu.exec import chunked as CH
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.sql.parser import parse

    for sql in (QUERIES[3], Q18_LOW):
        stmt = parse(sql)
        plan = plan_statement(s, stmt)
        assert CH.chunk_plan_needed(s, plan)
        # straight through the chunked runner: no silent whole-table
        # fallback can mask an Unchunkable here
        got = CH.run_chunked(s, stmt, sql)
        want = whole.sql(sql).rows
        assert want, "vacuously-empty oracle"
        assert norm(got.rows) == norm(want), (sql[:40], chunk_orders,
                                              mesh_n)


def test_bounded_accumulator_pipelined_loop(sessions):
    """When fixed-cap buffering of all chunks would exceed
    chunk_buffer_max_rows, the pipelined loop folds chunks into a
    bounded on-device accumulator instead of dropping to the per-chunk
    syncing loop (round-3 VERDICT item 4).  Results must match."""
    _, whole = sessions
    s = presto_tpu.connect(tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    s.properties["chunked_rows_threshold"] = 50_000
    s.properties["chunk_orders"] = 5_000   # ~15 chunks
    # small budget: cap * nchunks exceeds it, actual live rows do not
    s.properties["chunk_buffer_max_rows"] = 50_000
    from presto_tpu.exec import chunked as CH
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.sql.parser import parse

    acc_calls = {"hit": 0}
    orig = CH._FragmentRunner._chunk_loop_accumulate

    def spy(self, *a, **k):
        r = orig(self, *a, **k)
        if r is not None:
            acc_calls["hit"] += 1
        return r

    monkeypatch = pytest.MonkeyPatch()
    monkeypatch.setattr(CH._FragmentRunner, "_chunk_loop_accumulate", spy)
    try:
        # unbounded root (no LIMIT) + orderkey-skewed filter: chunk 0
        # calibrates a large cap, later chunks are sparse — the exact
        # shape fixed-cap buffering wastes HBM on
        group_q = ("SELECT l_orderkey, sum(l_quantity) q FROM lineitem "
                   "WHERE l_orderkey < 60000 GROUP BY l_orderkey "
                   "HAVING sum(l_quantity) > 50")
        for sql in (group_q,):
            stmt = parse(sql)
            assert CH.chunk_plan_needed(s, plan_statement(s, stmt))
            got = CH.run_chunked(s, stmt, sql)
            want = whole.sql(sql).rows
            assert want, "vacuously-empty oracle"
            assert norm(got.rows) == norm(want), sql[:40]
        assert acc_calls["hit"] >= 1, \
            "bounded accumulator path never engaged"
    finally:
        monkeypatch.undo()


def test_order_insensitive_walk():
    """The executor's order-insensitivity marking behind sort-order
    materialization (exec/gather.py): joins under an aggregation may
    reorder, anything under a Sort/TopN/Limit may not, semi-join build
    sides always may."""
    from presto_tpu import types as T
    from presto_tpu.exec.executor import Executor
    from presto_tpu.plan import nodes as P
    from presto_tpu.plan.ir import AggCall, Ref

    scan_a = P.TableScan("a", {"x": "x"}, {"x": T.BIGINT})
    scan_b = P.TableScan("b", {"y": "y"}, {"y": T.BIGINT})
    join = P.Join(scan_a, scan_b, "INNER", [("x", "y")])
    agg = P.Aggregate(join, ["x"], {"c": AggCall("count", (), T.BIGINT)},
                      step="PARTIAL")
    ex = Executor.__new__(Executor)  # walk needs no session
    ex.mark_order_insensitive(agg, root_flag=True)
    assert ex._order_ok(agg) and ex._order_ok(join)
    assert ex._order_ok(scan_a) and ex._order_ok(scan_b)

    # under a TopN the join's order shows through (tie-breaking)
    topn = P.TopN(join, [("x", True, None)], 10)
    ex2 = Executor.__new__(Executor)
    ex2.mark_order_insensitive(topn, root_flag=False)
    assert not ex2._order_ok(join)

    # semi-join build side is a SET even under an order-sensitive root
    semi = P.Join(scan_a, scan_b, "SEMI", [("x", "y")])
    lim = P.Limit(semi, 5)
    ex3 = Executor.__new__(Executor)
    ex3.mark_order_insensitive(lim, root_flag=False)
    assert not ex3._order_ok(semi)
    assert not ex3._order_ok(scan_a)
    assert ex3._order_ok(scan_b)

    # order-sensitive aggregates pin their input order
    agg2 = P.Aggregate(join, ["x"],
                       {"v": AggCall("array_agg", (Ref("x", T.BIGINT),),
                                     T.BIGINT)})
    ex4 = Executor.__new__(Executor)
    ex4.mark_order_insensitive(agg2, root_flag=True)
    assert not ex4._order_ok(join)

    # a DAG node feeding BOTH an order-free and an order-pinned
    # consumer must stay unmarked (AND over paths)
    shared = P.Join(scan_a, scan_b, "INNER", [("x", "y")])
    both = P.Union([P.Aggregate(shared, ["x"], {}),
                    P.TopN(shared, [("x", True, None)], 3)],
                   ["x"], [{"x": "x"}, {"x": "x"}])
    ex5 = Executor.__new__(Executor)
    ex5.mark_order_insensitive(both, root_flag=True)
    assert not ex5._order_ok(shared)


def test_chunked_sort_order_materialization(sessions, monkeypatch):
    """Force the gather-staging tier on at test sizes: the chunked
    join-under-partial-agg programs then run the Pallas block-gather /
    sort-order materialization paths (interpret mode on CPU) and must
    still match whole-table results exactly."""
    from presto_tpu.exec import gather as G

    monkeypatch.setenv("PRESTO_TPU_GATHER", "force")
    monkeypatch.setattr(G, "_STAGED_MIN_INDICES", 1)
    monkeypatch.setattr(G, "_IB", 64)
    monkeypatch.setattr(G, "_MAX_WINDOW", 512)
    staged = presto_tpu.connect(
        tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    staged.properties["chunked_rows_threshold"] = 50_000
    staged.properties["chunk_orders"] = 20_000
    _, whole = sessions
    # Q18: expanding join under a partial aggregate — the exact shape
    # the sort-order/blocked tier targets (Q3 rides the same kernels
    # via test_chunked_matches_whole)
    got = staged.sql(QUERIES[18])
    want = whole.sql(QUERIES[18])
    assert norm(got.rows) == norm(want.rows)
