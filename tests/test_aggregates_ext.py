"""Round-5 aggregate batch: set_agg/set_union, map_union_sum,
approx_most_frequent, min_by/max_by(x, y, n), reduce_agg.

Reference: presto-main/.../operator/aggregation/ —
SetAggregationFunction, SetUnionFunction, MapUnionSumAggregation,
ApproximateMostFrequent, MinMaxByNAggregationFunction,
ReduceAggregationFunction.
"""

import pytest

import presto_tpu
from presto_tpu.catalog import Catalog


@pytest.fixture(scope="module")
def s():
    return presto_tpu.connect(Catalog())


def one(s, sql):
    rows = s.sql(sql).rows
    assert len(rows) == 1
    return rows[0][0]


def test_set_agg_dedups(s):
    assert one(s, "SELECT set_agg(x) FROM "
               "(VALUES (1),(2),(1),(3),(2)) AS t(x)") == (1, 2, 3)


def test_set_agg_grouped(s):
    rows = s.sql("SELECT g, set_agg(x) FROM (VALUES (1,'a'),(1,'b'),"
                 "(1,'a'),(2,'c')) AS t(g,x) GROUP BY g ORDER BY g").rows
    assert rows == [(1, ("a", "b")), (2, ("c",))]


def test_set_union(s):
    assert one(s, "SELECT set_union(a) FROM (SELECT ARRAY[1,2] AS a "
               "UNION ALL SELECT ARRAY[2,3])") == (1, 2, 3)


def test_map_union_sum(s):
    assert one(s, "SELECT map_union_sum(m) FROM "
               "(SELECT MAP(ARRAY['a','b'], ARRAY[1,2]) AS m UNION ALL "
               "SELECT MAP(ARRAY['b','c'], ARRAY[10,20]))") == \
        (("a", 1), ("b", 12), ("c", 20))


def test_approx_most_frequent(s):
    assert one(s, "SELECT approx_most_frequent(2, x, 10) FROM (VALUES "
               "('a'),('b'),('a'),('c'),('a'),('b')) AS t(x)") == \
        (("a", 3), ("b", 2))


def test_min_max_by_n(s):
    assert one(s, "SELECT min_by(x, y, 2) FROM (VALUES ('a',3),('b',1),"
               "('c',2)) AS t(x,y)") == ("b", "c")
    assert one(s, "SELECT max_by(x, y, 2) FROM (VALUES ('a',3),('b',1),"
               "('c',2)) AS t(x,y)") == ("a", "c")
    # n larger than the group: whole group, ordered
    assert one(s, "SELECT max_by(x, y, 9) FROM (VALUES ('a',1),('b',2))"
               " AS t(x,y)") == ("b", "a")


def test_min_max_by_2arg_still_scalar(s):
    assert one(s, "SELECT min_by(x, y) FROM (VALUES ('a',3),('b',1))"
               " AS t(x,y)") == "b"


def test_reduce_agg_sum(s):
    assert one(s, "SELECT reduce_agg(x, 0, (s, v) -> s + v, "
               "(a, b) -> a + b) FROM (VALUES (1),(2),(3),(4)) "
               "AS t(x)") == 10


def test_reduce_agg_grouped_product(s):
    rows = s.sql("SELECT g, reduce_agg(x, 1, (s, v) -> s * v, "
                 "(a, b) -> a * b) FROM (VALUES (1,2),(1,3),(2,5)) "
                 "AS t(g,x) GROUP BY g ORDER BY g").rows
    assert rows == [(1, 6), (2, 5)]


def test_reduce_agg_double_state(s):
    # state widens via the cast the analyzer inserts on the lambda body
    assert one(s, "SELECT reduce_agg(x, 0.0, (s, v) -> s + v * v, "
               "(a, b) -> a + b) FROM (VALUES (1),(2),(3)) AS t(x)") == \
        pytest.approx(14.0)


def test_empty_groups_are_null(s):
    assert one(s, "SELECT set_agg(x) FROM (VALUES "
               "(CAST(NULL AS INTEGER))) AS t(x)") is None


def test_evaluate_classifier_predictions(s):
    r = one(s, "SELECT evaluate_classifier_predictions(t, p) FROM "
            "(VALUES ('a','a'),('a','b'),('b','b'),('b','b')) AS x(t,p)")
    assert r.splitlines()[0] == "Accuracy: 3/4 (75.00%)"
    assert "Precision(b): 2/3 (66.67%)" in r
    assert "Recall(a): 1/2 (50.00%)" in r


def test_approx_percentile_array_form(s):
    assert one(s, "SELECT approx_percentile(x, ARRAY[0.25, 0.5, 0.75]) "
               "FROM (VALUES (1),(2),(3),(4)) AS t(x)") == (1, 2, 3)
    rows = s.sql("SELECT g, approx_percentile(x, ARRAY[0.5]) FROM "
                 "(VALUES (1,1),(1,9),(2,5)) AS t(g,x) GROUP BY g "
                 "ORDER BY g").rows
    assert rows == [(1, (1,)), (2, (5,))]


def test_approx_percentile_weighted(s):
    # weight 10 on the value 3 pulls the median to 3
    assert one(s, "SELECT approx_percentile(x, w, 0.5) FROM "
               "(VALUES (1,1),(2,1),(3,10)) AS t(x,w)") == 3
    assert one(s, "SELECT approx_percentile(x, w, ARRAY[0.5, 0.9]) "
               "FROM (VALUES (1.0,1),(2.0,1),(3.0,10)) AS t(x,w)") == \
        (3.0, 3.0)


def test_interval_sum_avg(s):
    r = s.sql("SELECT sum(d), avg(d) FROM (VALUES (INTERVAL '1' DAY), "
              "(INTERVAL '2' DAY)) AS t(d)").rows
    assert r == [(3 * 86400 * 1_000_000, 3 * 86400 * 1_000_000 // 2)]


def test_classification_metrics(s):
    base = ("(VALUES (true, 0.9), (false, 0.6), (true, 0.3), "
            "(false, 0.1)) AS x(t, p)")
    assert one(s, f"SELECT classification_thresholds(4, t, p) "
               f"FROM {base}") == (0.0, 0.25, 0.5, 0.75)
    # at threshold 0.5: called positive = {0.9, 0.6}; TP=1 FP=1
    prec = one(s, f"SELECT classification_precision(4, t, p) FROM {base}")
    assert prec[2] == pytest.approx(0.5)
    rec = one(s, f"SELECT classification_recall(4, t, p) FROM {base}")
    assert rec[2] == pytest.approx(0.5)
    miss = one(s, f"SELECT classification_miss_rate(4, t, p) FROM {base}")
    assert miss[2] == pytest.approx(0.5)
    fo = one(s, f"SELECT classification_fall_out(4, t, p) FROM {base}")
    assert fo[2] == pytest.approx(0.5)


def test_classification_rejects_bad_predictions(s):
    with pytest.raises(Exception, match="0, 1"):
        s.sql("SELECT classification_precision(2, t, p) FROM "
              "(VALUES (true, 1.5)) AS x(t, p)")
