"""Verifier + benchmark suite tests (reference analogs: the
presto-verifier unit tests and BenchmarkSuite smoke runs)."""

import presto_tpu
from presto_tpu.verifier import (Verifier, report, row_checksum,
                                 session_runner, sqlite_runner)


def test_row_checksum_order_insensitive():
    a = [(1, "x", 1.5), (2, "y", None)]
    b = [(2, "y", None), (1, "x", 1.5)]
    assert row_checksum(a) == row_checksum(b)
    assert row_checksum(a) != row_checksum([(1, "x", 1.5)])
    # float canonicalization absorbs sub-tolerance noise
    assert row_checksum([(1.00000001,)]) == row_checksum([(1.00000002,)])
    assert row_checksum([(1.0,)]) != row_checksum([(2.0,)])


def test_verifier_match_and_mismatch(tpch_catalog_tiny, tpch_sqlite_tiny):
    s = presto_tpu.connect(tpch_catalog_tiny)
    v = Verifier(sqlite_runner(tpch_sqlite_tiny), session_runner(s))
    results = v.run({
        "counts": "SELECT count(*) FROM nation",
        "joins": "SELECT n_name, count(*) AS c FROM customer, nation "
                 "WHERE c_nationkey = n_nationkey GROUP BY n_name",
        "bad_sql": "SELECT nocol FROM nation",
    })
    by_name = {r.name: r for r in results}
    assert by_name["counts"].state == "MATCH"
    assert by_name["joins"].state == "MATCH"
    # control (sqlite) fails first on bad SQL: CONTROL_FAIL wins
    assert by_name["bad_sql"].state == "CONTROL_FAIL"
    txt = report(results)
    assert "MATCH=2" in txt and "CONTROL_FAIL=1" in txt
    # test-side-only failure
    v2 = Verifier(lambda sql: [(1,)], session_runner(s))
    assert v2.verify_one("t", "SELECT nocol FROM nation").state == "TEST_FAIL"


def test_verifier_detects_difference(tpch_catalog_tiny):
    s = presto_tpu.connect(tpch_catalog_tiny)
    control = lambda sql: [(1,)]
    v = Verifier(control, session_runner(s))
    r = v.verify_one("x", "SELECT 2")
    assert r.state == "MISMATCH"


def test_benchmark_suite_runs(tpch_catalog_tiny):
    from presto_tpu.benchmarks import build_default_suite

    s = presto_tpu.connect(tpch_catalog_tiny)
    suite = build_default_suite(s, 0.01)
    suite.runs = 1
    results = suite.run("sql_tpch_q6")
    assert len(results) == 1
    assert results[0].median_ms > 0 and results[0].rows_per_sec > 0
