"""Plan-quality perf gate (round-3 VERDICT item 1: the ReorderJoins
regression shipped because no in-repo gate timed a query).

Absolute wall-clock is too noisy on shared CI hosts, so the default
suite gates RELATIVE plan quality: the cost-based optimizer may never
make a query meaningfully slower than the greedy order it replaces —
the exact failure mode that shipped `vs_baseline 0.98` in round 3.
bench.py separately gates absolute warm times on the real chip against
tests/perf_reference.json and reports `perf_gate` in its JSON line.
"""

import time

import pytest

import presto_tpu
from presto_tpu.catalog import tpch_catalog

from tpch_queries import QUERIES

SF = 0.1
# ON may be this much slower than OFF before the gate trips.  Generous
# to absorb CI noise; the round-3 regression was 4.6x.
MAX_RATIO = 1.3


def _warm_best(session, sql, runs=3):
    session.sql(sql)  # compile + warm
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        session.sql(sql)
        best = min(best, time.perf_counter() - t0)
    return best


@pytest.mark.slow
def test_reorder_joins_never_deoptimizes():
    """Tier 2: a best-of-N wall-clock comparison needs ~20s of repeated
    compiles on the 1-core CI box and is timing-noisy there anyway."""
    cat = tpch_catalog(SF, cache_dir="/tmp/presto_tpu_cache")
    on = presto_tpu.connect(cat)
    off = presto_tpu.connect(cat)
    off.set("reorder_joins", False)
    for qid in (3, 18):
        t_on = _warm_best(on, QUERIES[qid])
        t_off = _warm_best(off, QUERIES[qid])
        assert t_on <= t_off * MAX_RATIO, (
            f"Q{qid}: reorder_joins=True {t_on * 1000:.0f}ms vs "
            f"False {t_off * 1000:.0f}ms — the CBO de-optimized the "
            f"query (round-3 regression class)")
