"""Coordinator fleet tests (ISSUE 16): the ownership ring, slot-lease
board, signature-affinity front-door routing (proxy and 307-redirect),
fleet-scale query coalescing, and cross-coordinator cache coherence —
including the dropped-broadcast fault leg, where the catalog-version key
(PR-9) must carry correctness alone.

Reference analogs: disaggregated-coordinator Presto's ResourceManager /
coordinator discovery; here the ring + leases + gossip live in
server/fleet.py and every coordinator stays able to execute every
statement (routing is an optimization, never a correctness surface)."""

import json
import os
import tempfile
import threading
import urllib.request

import numpy as np
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.client import StatementClient, connect_http
from presto_tpu.client.statement import QueryError
from presto_tpu.server import PrestoTpuServer
from presto_tpu.server import fleet as FL


# ---------------------------------------------------------------------------
# ownership ring
# ---------------------------------------------------------------------------


def test_ring_owner_stable_and_identical_across_instances():
    """Every member must derive the IDENTICAL ring from the same
    membership (blake2b, not per-process-salted hash()), regardless of
    join order."""
    a = FL.OwnershipRing()
    b = FL.OwnershipRing()
    for m in ("c1", "c2", "c3"):
        a.add(m)
    for m in ("c3", "c1", "c2"):
        b.add(m)
    keys = [f"sig{i}" for i in range(500)]
    assert [a.owner(k) for k in keys] == [b.owner(k) for k in keys]


def test_ring_distribution_roughly_balanced():
    ring = FL.OwnershipRing()
    for m in ("c1", "c2", "c3", "c4"):
        ring.add(m)
    counts = {}
    n = 4000
    for i in range(n):
        counts[ring.owner(f"k{i}")] = counts.get(ring.owner(f"k{i}"), 0) + 1
    for m in ("c1", "c2", "c3", "c4"):
        # 64 vnodes/member keep the spread well inside 2x of fair share
        assert n / 8 < counts[m] < n / 2, counts


def test_ring_rebalance_moves_about_k_over_n_keys():
    """Join moves ~K/N keys; leave restores the ORIGINAL owners of the
    moved arc (consistent hashing's whole point: a crash reshuffles one
    arc, not the key space)."""
    ring = FL.OwnershipRing()
    for m in ("c1", "c2", "c3"):
        ring.add(m)
    keys = [f"k{i}" for i in range(3000)]
    before = {k: ring.owner(k) for k in keys}
    ring.add("c4")
    moved = [k for k in keys if ring.owner(k) != before[k]]
    # expected 1/4; allow generous variance either side
    assert 0.12 * len(keys) < len(moved) < 0.40 * len(keys), len(moved)
    # every moved key moved TO the joiner, never between old members
    assert all(ring.owner(k) == "c4" for k in moved)
    ring.remove("c4")
    assert {k: ring.owner(k) for k in keys} == before


def test_ring_empty_and_single_member():
    ring = FL.OwnershipRing()
    assert ring.owner("x") is None
    ring.add("only")
    assert ring.owner("x") == "only"


# ---------------------------------------------------------------------------
# affinity keys
# ---------------------------------------------------------------------------


def test_affinity_key_classes():
    assert FL.affinity_key("EXECUTE my_q USING 1, 2") == "prepared::my_q"
    assert FL.affinity_key("  execute My_Q(3)") == "prepared::my_q"
    # ad-hoc reads key on normalized text
    k1 = FL.affinity_key("SELECT  1\nFROM t")
    assert k1 == FL.affinity_key("SELECT 1 FROM t")
    # writes / DDL / PREPARE have no affinity (run wherever they land)
    assert FL.affinity_key("INSERT INTO t VALUES (1)") is None
    assert FL.affinity_key("PREPARE p FROM SELECT 1") is None
    assert FL.affinity_key("CREATE TABLE x AS SELECT 1") is None
    assert FL.affinity_key("") is None


# ---------------------------------------------------------------------------
# slot-lease board
# ---------------------------------------------------------------------------


def test_slot_lease_caps_and_reclaim():
    b = FL.SlotLeaseBoard()
    b.register_worker("http://w1", 2)
    assert b.lease("A", "http://w1")
    assert b.lease("A", "http://w1")
    # saturated: a zero-budget lease fails instead of oversubscribing
    assert not b.lease("B", "http://w1", timeout_s=0.01)
    st = b.stats()
    assert st["inFlight"] == 2 and st["leaseWaits"] == 1
    # dead-coordinator sweep frees EVERY lease it held
    assert b.reclaim("A") == 2
    assert b.stats()["inFlight"] == 0
    assert b.lease("B", "http://w1", timeout_s=0.01)
    # release is idempotent per-held-lease
    b.release("B", "http://w1")
    b.release("B", "http://w1")
    assert b.stats()["inFlight"] == 0


def test_slot_lease_unregistered_worker_is_unmanaged():
    """Single-coordinator compatibility: workers nobody registered lease
    freely (no board entry = no cap to enforce)."""
    b = FL.SlotLeaseBoard()
    for _ in range(10):
        assert b.lease("A", "http://unknown")
    assert b.stats()["inFlight"] == 0


def test_slot_lease_blocks_until_release():
    b = FL.SlotLeaseBoard()
    b.register_worker("http://w1", 1)
    assert b.lease("A", "http://w1")
    got = []

    def waiter():
        got.append(b.lease("B", "http://w1", timeout_s=10.0))

    t = threading.Thread(target=waiter)
    t.start()
    b.release("A", "http://w1")
    t.join(timeout=10)
    assert got == [True]
    assert b.stats()["leaseWaits"] == 1


def test_directory_leave_shrinks_ring_and_reclaims_leases():
    d = FL.FleetDirectory()
    a = d.join("A", "http://a")
    d.join("B", "http://b")
    d.slots.register_worker("http://w1", 4)
    assert a.lease_slot("http://w1") and a.lease_slot("http://w1")
    assert d.ring.members() == ["A", "B"]
    assert d.leave("A") == 2  # reclaimed-lease count
    assert d.ring.members() == ["B"]
    assert d.slots.stats()["inFlight"] == 0


# ---------------------------------------------------------------------------
# front door: proxy vs redirect equivalence over live servers
# ---------------------------------------------------------------------------


def _session(**props):
    s = presto_tpu.connect(**props)
    s.catalog.register_memory(
        "t", {"k": T.BIGINT, "x": T.DOUBLE, "g": T.BIGINT},
        {"k": np.arange(200, dtype=np.int64),
         "x": np.arange(200, dtype=np.float64) * 1.5,
         "g": np.arange(200, dtype=np.int64) % 7})
    return s


def _two_door_fleet(**props):
    """Two in-process coordinators over ONE shared catalog object (the
    in-process fleet topology: version-keyed caches see the same bumps),
    joined through a FleetDirectory."""
    # journaling is default-ON for fleeted coordinators; isolate each
    # fleet's journal so reused coord ids ("A"/"B") across the suite
    # never see one another's entries through the shared spill base
    props.setdefault("query_journal_path",
                     tempfile.mkdtemp(prefix="pt_fleet_journal_"))
    d = FL.FleetDirectory()
    sa = _session(**props)
    sb = presto_tpu.connect(**props)
    sb.catalog = sa.catalog
    srv_a = PrestoTpuServer(sa).start()
    srv_b = PrestoTpuServer(sb).start()
    ma = d.join("A", srv_a.uri)
    mb = d.join("B", srv_b.uri)
    srv_a.attach_fleet(ma)
    srv_b.attach_fleet(mb)
    return d, (srv_a, ma), (srv_b, mb)


def test_proxy_routes_execute_to_ring_owner():
    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet()
    try:
        connect_http(srv_a.uri).execute(
            "PREPARE pq FROM SELECT count(*) c FROM t WHERE k < ?")
        key = FL.affinity_key("EXECUTE pq USING 120")
        owner = d.ring.owner(key)
        non_owner = srv_a if owner == "B" else srv_b
        owner_srv = srv_b if owner == "B" else srv_a
        rows = connect_http(non_owner.uri).execute(
            "EXECUTE pq USING 120").fetchall()
        assert rows == [(120,)]
        assert non_owner.fleet_counters["proxied"] == 1
        assert non_owner.fleet_counters["proxy_failures"] == 0
        assert owner_srv.fleet_counters["proxied"] == 0
        # the door that owns the signature executes locally
        rows2 = connect_http(owner_srv.uri).execute(
            "EXECUTE pq USING 50").fetchall()
        assert rows2 == [(50,)]
        assert owner_srv.fleet_counters["proxied"] == 0
    finally:
        srv_a.stop()
        srv_b.stop()


def test_redirect_mode_follows_307_to_owner_and_matches_proxy():
    """redirect-vs-proxy equivalence: the same EXECUTE through the same
    non-owner door returns identical rows in both modes; only the
    transport differs (Location hop vs server-side forward)."""
    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet()
    try:
        connect_http(srv_a.uri).execute(
            "PREPARE pq FROM SELECT count(*) c, sum(x) s FROM t "
            "WHERE k < ?")
        key = FL.affinity_key("EXECUTE pq USING 99")
        owner = d.ring.owner(key)
        non_owner = srv_a if owner == "B" else srv_b
        via_proxy = connect_http(non_owner.uri).execute(
            "EXECUTE pq USING 99").fetchall()
        non_owner.session.properties["fleet_affinity"] = "redirect"
        via_redirect = connect_http(non_owner.uri).execute(
            "EXECUTE pq USING 99").fetchall()
        assert via_proxy == via_redirect
        assert non_owner.fleet_counters["proxied"] == 1
        assert non_owner.fleet_counters["redirected"] == 1
    finally:
        srv_a.stop()
        srv_b.stop()


def test_affinity_off_executes_locally():
    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet(fleet_affinity="off")
    try:
        connect_http(srv_a.uri).execute(
            "PREPARE pq FROM SELECT count(*) c FROM t WHERE k < ?")
        for srv in (srv_a, srv_b):
            rows = connect_http(srv.uri).execute(
                "EXECUTE pq USING 30").fetchall()
            assert rows == [(30,)]
            assert srv.fleet_counters["proxied"] == 0
            assert srv.fleet_counters["redirected"] == 0
    finally:
        srv_a.stop()
        srv_b.stop()


def test_proxy_falls_back_to_local_when_owner_is_down():
    """Routing is an optimization, never a correctness surface: a dead
    owner means the non-owner executes the statement itself."""
    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet()
    try:
        connect_http(srv_a.uri).execute(
            "PREPARE pq FROM SELECT count(*) c FROM t WHERE k < ?")
        key = FL.affinity_key("EXECUTE pq USING 44")
        owner = d.ring.owner(key)
        owner_srv = srv_a if owner == "A" else srv_b
        non_owner = srv_b if owner == "A" else srv_a
        owner_srv.stop()
        rows = connect_http(non_owner.uri).execute(
            "EXECUTE pq USING 44").fetchall()
        assert rows == [(44,)]
        assert non_owner.fleet_counters["proxy_failures"] == 1
    finally:
        for srv in (srv_a, srv_b):
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — one is already stopped
                pass


def test_prepare_replicates_to_peers():
    """An EXECUTE landing on (or failing over to) ANY door finds the
    signature: PREPARE through one door best-effort replicates."""
    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet(fleet_affinity="off")
    try:
        connect_http(srv_a.uri).execute(
            "PREPARE pq FROM SELECT count(*) c FROM t WHERE k < ?")
        assert ma.counters["prepares_replicated"] == 1
        # executable on B WITHOUT routing (affinity off)
        rows = connect_http(srv_b.uri).execute(
            "EXECUTE pq USING 77").fetchall()
        assert rows == [(77,)]
    finally:
        srv_a.stop()
        srv_b.stop()


# ---------------------------------------------------------------------------
# fleet-scale coalescing: the affinity burst
# ---------------------------------------------------------------------------


def test_affinity_burst_forms_coalescing_batches_fleet_wide():
    """The tentpole's perf claim in miniature: concurrent EXECUTEs of
    ONE signature arrive at BOTH doors; the ring routes them all to the
    owner, whose vmap coalescer batches them — coalesce batches form at
    fleet scale instead of fragmenting per coordinator."""
    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet(
        coalesce_max_batch=4)
    try:
        connect_http(srv_a.uri).execute(
            "PREPARE pq FROM SELECT count(*) c, sum(x) s FROM t "
            "WHERE k < ?")
        key = FL.affinity_key("EXECUTE pq USING 1")
        owner_srv = srv_a if d.ring.owner(key) == "A" else srv_b
        # prewarm the batch-size buckets out of the asserted burst
        connect_http(owner_srv.uri).execute("EXECUTE pq USING 5")
        before = (owner_srv.serving.coalescer_stats() or {})
        errs = []

        def client(sid):
            uri = (srv_a if sid % 2 == 0 else srv_b).uri
            for i in range(8):
                try:
                    rows = connect_http(uri).execute(
                        f"EXECUTE pq USING {10 + sid * 8 + i}").fetchall()
                    assert rows == [(10 + sid * 8 + i,
                                     pytest.approx((10 + sid * 8 + i - 1)
                                                   * (10 + sid * 8 + i)
                                                   / 2 * 1.5))]
                except Exception as e:  # noqa: BLE001
                    errs.append(f"{type(e).__name__}: {e}")

        ths = [threading.Thread(target=client, args=(sid,))
               for sid in range(6)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        after = (owner_srv.serving.coalescer_stats() or {})
        assert not errs
        assert after.get("batches", 0) > before.get("batches", 0)
        # the burst really crossed doors: half the clients hit the
        # non-owner and were routed
        non_owner = srv_b if owner_srv is srv_a else srv_a
        assert non_owner.fleet_counters["proxied"] > 0
    finally:
        srv_a.stop()
        srv_b.stop()


def test_coordinator_crash_reprepare_is_transparent():
    """The owner dies holding the only copy of a signature (replication
    was dropped): EXECUTE through the survivor surfaces the TYPED
    unknown-prepared error — never a wrong result — and a re-PREPARE
    there makes the same EXECUTE succeed."""
    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet()
    try:
        ma.drop_broadcasts = True  # replication never reaches B
        connect_http(srv_a.uri).execute(
            "PREPARE pq FROM SELECT count(*) c FROM t WHERE k < ?")
        assert ma.counters["prepares_replicated"] == 0
        srv_a.stop()
        d.leave("A")  # heartbeat failure detector's verdict
        with pytest.raises(QueryError) as ei:
            connect_http(srv_b.uri).execute(
                "EXECUTE pq USING 10").fetchall()
        assert "not found" in str(ei.value)
        connect_http(srv_b.uri).execute(
            "PREPARE pq FROM SELECT count(*) c FROM t WHERE k < ?")
        rows = connect_http(srv_b.uri).execute(
            "EXECUTE pq USING 10").fetchall()
        assert rows == [(10,)]
    finally:
        srv_b.stop()


# ---------------------------------------------------------------------------
# cross-coordinator cache coherence (belt AND suspenders)
# ---------------------------------------------------------------------------


def test_write_through_a_never_leaves_stale_hit_on_b():
    """CTAS/INSERT through door A must not let door B serve a pre-write
    cached result — covered by the invalidation broadcast (belt) AND,
    in the second leg, with broadcasts DROPPED, by the catalog
    token+version already in every cache key (suspenders)."""
    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet(fleet_affinity="off")
    try:
        q = "SELECT count(*) c FROM t"
        assert connect_http(srv_b.uri).execute(q).fetchall() == [(200,)]
        # cached on B now
        assert connect_http(srv_b.uri).execute(q).fetchall() == [(200,)]
        # leg 1: broadcast delivered — B's cache is invalidated promptly
        connect_http(srv_a.uri).execute(
            "INSERT INTO t VALUES (1000, 1.0, 0)")
        assert mb.counters["invalidations_received"] >= 1
        assert connect_http(srv_b.uri).execute(q).fetchall() == [(201,)]
        # leg 2: the broadcast is dropped (fault hook) — the bumped
        # catalog version makes B's key MISS; never a stale hit
        ma.drop_broadcasts = True
        received_before = mb.counters["invalidations_received"]
        connect_http(srv_a.uri).execute(
            "INSERT INTO t VALUES (1001, 2.0, 1)")
        assert ma.counters["invalidations_dropped"] >= 1
        assert mb.counters["invalidations_received"] == received_before
        assert connect_http(srv_b.uri).execute(q).fetchall() == [(202,)]
    finally:
        srv_a.stop()
        srv_b.stop()


def test_fleet_invalidate_knob_disables_broadcast_not_correctness():
    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet(
        fleet_affinity="off", fleet_invalidate=False)
    try:
        q = "SELECT sum(k) s FROM t"
        base = connect_http(srv_b.uri).execute(q).fetchall()
        connect_http(srv_a.uri).execute(
            "INSERT INTO t VALUES (5000, 0.0, 0)")
        assert ma.counters["invalidations_sent"] == 0
        got = connect_http(srv_b.uri).execute(q).fetchall()
        assert got == [(base[0][0] + 5000,)]
    finally:
        srv_a.stop()
        srv_b.stop()


# ---------------------------------------------------------------------------
# peer health gossip at the cluster layer
# ---------------------------------------------------------------------------


def test_peer_health_gossip_benches_worker_on_survivors():
    """Coordinator A quarantines a worker; the gossiped verdict trips
    B's breaker WITHOUT local evidence (retry.HealthBoard.force_open)
    and removes the worker from B's schedulable set.  Recovery is never
    gossip's call: probation still applies locally."""
    from presto_tpu.parallel import cluster as C

    d = FL.FleetDirectory()
    ma = d.join("A", "http://a.invalid")
    mb = d.join("B", "http://b.invalid")
    bad, ok = "http://127.0.0.1:9", "http://127.0.0.1:10"
    cb = C.ClusterSession(presto_tpu.connect(), [bad, ok], fleet=mb)
    assert bad in cb.workers
    # A's quarantine site gossips exactly this
    ma.gossip_health(bad, "open")
    assert mb.counters["health_gossip_received"] == 1
    assert cb.health.state(bad) == "open"
    assert bad not in cb.workers and ok in cb.workers
    # a 'closed' verdict is ignored — recovery needs LOCAL probation
    ma.gossip_health(bad, "closed")
    assert cb.health.state(bad) == "open"
    assert bad not in cb.workers


# ---------------------------------------------------------------------------
# introspection
# ---------------------------------------------------------------------------


def test_fleet_stats_ride_info_and_metrics():
    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet()
    try:
        info = json.loads(urllib.request.urlopen(
            srv_a.uri + "/v1/info", timeout=30).read())
        assert info["fleet"]["coordId"] == "A"
        assert info["fleet"]["ring"] == ["A", "B"]
        assert "slots" in info["fleet"]
        scrape = urllib.request.urlopen(
            srv_a.uri + "/v1/metrics", timeout=30).read().decode()
        assert "presto_tpu_fleet_coordinators 2" in scrape
    finally:
        srv_a.stop()
        srv_b.stop()


def test_watch_fleet_unregisters_dead_coordinator():
    """Discovery integration: the heartbeat failure detector maps a dead
    coordinator URI to directory.leave — ring shrinks, leases reclaim —
    without an explicit goodbye."""
    from presto_tpu.server import discovery as D

    d = FL.FleetDirectory()
    a = d.join("A", "http://127.0.0.1:1")  # nothing listens: born dead
    sb = _session()
    srv_b = PrestoTpuServer(sb).start()
    d.join("B", srv_b.uri)
    d.slots.register_worker("http://w1", 2)
    assert a.lease_slot("http://w1")
    det = D.watch_fleet(d, interval=0.05).start()
    try:
        import time as _time

        t0 = _time.monotonic()
        while "A" in d.ring.members() \
                and _time.monotonic() - t0 < FL.GOSSIP_TIMEOUT_S * 10:
            _time.sleep(0.05)
        assert d.ring.members() == ["B"]
        assert d.slots.stats()["inFlight"] == 0  # A's leases reclaimed
    finally:
        det.stop()
        srv_b.stop()


# ---------------------------------------------------------------------------
# journaled in-flight query failover (ISSUE 17)
# ---------------------------------------------------------------------------


def test_adopter_determinism_and_journal_replication():
    """Adoption safety: every survivor derives the SAME ring successor
    for a dead coordinator (pure function of the post-leave ring — no
    coordination round), exactly one member volunteers, and journal
    entries replicate best-effort over the directory relay."""
    d = FL.FleetDirectory()
    d.join("A", "http://a.invalid")
    mb = d.join("B", "http://b.invalid")
    mc = d.join("C", "http://c.invalid")
    d.leave("A")
    assert mb.adopter_of("A") == mc.adopter_of("A")
    assert [m.should_adopt("A")
            for m in (mb, mc)].count(True) == 1
    got = []
    mc.subscribe(on_journal=got.append)
    entry = {"queryId": "q1", "sql": "SELECT 1", "coord": "B",
             "state": "RUNNING"}
    assert mb.replicate_journal(entry) >= 1
    assert got and got[0]["queryId"] == "q1"
    assert mc.counters["journal_received"] == 1


def test_coordinator_death_adoption_completes_polling_client():
    """Tentpole acceptance: coordinator A dies with an in-flight
    journaled query; a client polling the OTHER door's statement URI
    for that query id is held in RUNNING while the ring successor
    adopts it from the journal, then receives the finished rows — the
    client never sees 'unknown query'."""
    import time as _time

    from presto_tpu.parallel import journal as _J

    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet()
    try:
        root = srv_b.session.properties["query_journal_path"]
        qid = "20260806_000000_00042_chaos"
        # A journaled the query, then died before cleanup could run
        assert _J.QueryJournal(root, "A").write(
            _J.entry_for(qid, "SELECT count(*) c FROM t", "A", {}))
        srv_a.stop()
        d.leave("A")  # failure detector's verdict -> B adopts (thread)
        deadline = _time.monotonic() + 30.0
        rows, state = [], None
        url = f"{srv_b.uri}/v1/statement/{qid}/0"
        while _time.monotonic() < deadline:
            payload = json.loads(
                urllib.request.urlopen(url, timeout=30).read())
            state = payload.get("stats", {}).get("state")
            if state == "FINISHED":
                rows = payload.get("data", [])
                break
            assert state in ("QUEUED", "RUNNING"), payload
            url = payload["nextUri"]  # RUNNING-hold re-points at B
            _time.sleep(0.05)
        assert state == "FINISHED"
        assert rows == [[200]]
        assert srv_b.fleet_counters["queries_adopted"] >= 1
        t0 = _time.monotonic()
        while any(n.endswith(_J.SUFFIX) for n in os.listdir(root)) \
                and _time.monotonic() - t0 < 10.0:
            _time.sleep(0.05)  # entry retired once the adoption lands
        assert not any(n.endswith(_J.SUFFIX) for n in os.listdir(root))
    finally:
        srv_b.stop()


def test_statement_client_fails_over_to_backup_door():
    """StatementClient with backup_uris: the primary door is dead at
    submit time — the POST fails over to the backup door and the query
    runs there; server_uri re-points so every later poll goes to the
    survivor directly."""
    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet()
    try:
        dead_uri = srv_a.uri
        srv_a.stop()
        d.leave("A")
        st = StatementClient(dead_uri, "SELECT sum(k) s FROM t",
                             backup_uris=[srv_b.uri])
        assert list(st.rows()) == [(sum(range(200)),)]
        assert st.server_uri == srv_b.uri
    finally:
        srv_b.stop()


def test_execute_owner_death_mid_coalesce_riders_survive():
    """Satellite (ISSUE 17): fleet-routed EXECUTEs whose affinity owner
    dies around the coalesce window.  Phase 1: the owner's batch leader
    is killed by a scripted fault — riders re-run solo, every client
    gets its own correct rows, zero surfaced failures.  Phase 2: the
    owner itself dies — the same burst through the surviving door
    re-routes (proxy failure -> local execution), identical results."""
    from presto_tpu.parallel import faults as F

    d, (srv_a, ma), (srv_b, mb) = _two_door_fleet(
        coalesce_window_ms=40, coalesce_max_batch=8)
    doors = {"A": srv_a, "B": srv_b}
    try:
        connect_http(srv_a.uri).execute(
            "PREPARE pq FROM SELECT count(*) c FROM t WHERE k < ?")
        assert ma.counters["prepares_replicated"] >= 1
        owner = d.ring.owner(FL.affinity_key("EXECUTE pq USING 120"))
        owner_srv = doors[owner]
        other_srv = doors["B" if owner == "A" else "A"]
        binds = [120, 120, 120, 50]  # same-signature riders + one solo

        def burst(door):
            out, errs = {}, []

            def one(i, n):
                try:
                    out[i] = connect_http(door.uri).execute(
                        f"EXECUTE pq USING {n}").fetchall()
                except Exception as e:  # noqa: BLE001 — collected
                    errs.append(e)

            ths = [threading.Thread(target=one, args=(i, n))
                   for i, n in enumerate(binds)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            return out, errs

        # phase 1: owner alive, its coalesce leader crashes mid-window
        F.install(F.FaultPlan.parse("coalesce:BATCH:*:1:fail"))
        try:
            out, errs = burst(other_srv)  # routed to the owner door
        finally:
            F.install(None)
        assert not errs, errs
        assert {i: v for i, v in out.items()} == {
            i: [(n,)] for i, n in enumerate(binds)}
        # phase 2: the owner dies; the survivor re-routes to itself
        owner_srv.stop()
        d.leave(owner)
        out2, errs2 = burst(other_srv)
        assert not errs2, errs2
        assert {i: v for i, v in out2.items()} == {
            i: [(n,)] for i, n in enumerate(binds)}
    finally:
        for s in (srv_a, srv_b):
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — already stopped
                pass
