"""Dynamic filtering (ISSUE 5): build-side runtime filters pushed into
probe scans.

Layers under test:
- exec/kernels.py rf_* family: CPU equivalence of the exact
  (searchsorted) and bloom membership probes against a numpy reference,
  across dtypes x masks x empty x all-pruned, plus the bloom sizing
  heuristic's false-positive rate and the host summary/union twins.
- plan/runtime_filters.py: producer/consumer annotation of q17-class
  plans, the kill switch, and domain merge (intersection) semantics.
- executor: dynamic mode counts pruned rows; compiled mode keeps the
  filter inside the trace; results are IDENTICAL with filtering on/off.
- exec/chunked.py: whole chunks whose zone ranges miss the runtime
  domain are skipped (df_chunks_pruned), results identical.
- parallel/cluster.py: in-fragment filters on broadcast-build joins and
  the coordinator-routed side channel for partitioned joins (partial
  summaries unioned per repartition bucket), observable via /v1/info.
"""

import json
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.batch import Column
from presto_tpu.exec import kernels as K
from presto_tpu.plan import runtime_filters as RF
from presto_tpu.plan.domains import merge_domain_maps
from presto_tpu.storage.shard import Domain

from tpch_queries import QUERIES


def norm(rows):
    return [tuple(round(v, 2) if isinstance(v, float) else v for v in r)
            for r in rows]


# ---------------------------------------------------------------------------
# kernel units: exact + bloom membership vs numpy reference
# ---------------------------------------------------------------------------


def _ref_mask(build_vals, build_live, probe_vals, probe_valid):
    keep = set(np.asarray(build_vals)[np.asarray(build_live)].tolist())
    return np.asarray([bool(v) and (x in keep)
                       for x, v in zip(np.asarray(probe_vals).tolist(),
                                       np.asarray(probe_valid).tolist())])


@pytest.mark.parametrize("dtype", [np.int64, np.int32, np.int16])
@pytest.mark.parametrize("structure", ["exact", "bloom"])
@pytest.mark.parametrize("case", ["plain", "masked", "empty", "all_pruned"])
def test_rf_membership_equivalence(dtype, structure, case):
    rng = np.random.default_rng(7)
    nb, npr = 300, 2000
    if case == "empty":
        bvals = np.zeros((0,), dtype)
        blive = np.zeros((0,), bool)
    else:
        bvals = rng.integers(0, 500, nb).astype(dtype)
        blive = np.ones(nb, bool)
        if case == "masked":
            blive[::3] = False
    if case == "all_pruned":
        pvals = (rng.integers(600, 900, npr)).astype(dtype)  # disjoint
    else:
        pvals = rng.integers(0, 700, npr).astype(dtype)
    pvalid = np.ones(npr, bool)
    pvalid[::7] = False  # NULL probe keys: always prunable

    t = {np.int64: T.BIGINT, np.int32: T.INTEGER, np.int16: T.SMALLINT}[dtype]
    bcol = Column(jnp.asarray(bvals), None, t, None)
    pcol = Column(jnp.asarray(pvals), jnp.asarray(pvalid), t, None)
    summary = K.rf_build(bcol, jnp.asarray(blive), structure=structure)
    mask = np.asarray(K.rf_probe(summary, pcol))
    ref = _ref_mask(bvals, blive, pvals, pvalid)
    if structure == "exact":
        assert (mask == ref).all()
    else:
        # bloom contract: false positives allowed, false negatives never
        assert (mask | ~ref).all(), "bloom dropped a matching row"
        if case == "all_pruned":
            assert mask.mean() < 0.10  # and it does actually prune


def test_rf_bloom_auto_routing_and_fpr():
    """Builds over RF_EXACT_MAX route to bloom; the sizing heuristic
    (RF_BLOOM_BITS_PER_KEY bits/key, k=3) keeps the measured
    false-positive rate inside ~4x the analytic ~0.5%."""
    rng = np.random.default_rng(3)
    nb = 1 << 12
    bvals = np.unique(rng.integers(0, 1 << 40, nb)).astype(np.int64)
    bcol = Column(jnp.asarray(bvals), None, T.BIGINT, None)
    live = jnp.ones((bvals.size,), bool)
    auto = K.rf_build(bcol, live)
    assert auto["kind"] == "exact"  # small build: exact wins
    bloom = K.rf_build(bcol, live, structure="bloom")
    # 100k probes guaranteed OUTSIDE the build set: any hit is a FP
    pvals = rng.integers(1 << 41, 1 << 42, 100_000).astype(np.int64)
    pcol = Column(jnp.asarray(pvals), None, T.BIGINT, None)
    fpr = float(np.asarray(K.rf_probe(bloom, pcol)).mean())
    assert fpr < 0.02, fpr


def test_rf_host_summary_union_and_device_roundtrip():
    a = K.rf_summary_host(np.asarray([5, 1, 3, 3]))
    b = K.rf_summary_host(np.asarray([8, 2]))
    assert a == {"lo": 1, "hi": 5, "vals": [1, 3, 5]}
    u = K.rf_union_host([a, b])
    assert u == {"lo": 1, "hi": 8, "vals": [1, 2, 3, 5, 8]}
    # an inexact part degrades the union to a domain
    big = {"lo": 0, "hi": 100, "vals": None}
    assert K.rf_union_host([a, big])["vals"] is None
    # empty build -> impossible filter -> prunes every probe row
    empty = K.rf_host_to_device(K.rf_summary_host(np.asarray([])))
    pcol = Column(jnp.asarray(np.arange(16)), None, T.BIGINT, None)
    assert not np.asarray(K.rf_probe(empty, pcol)).any()
    dev = K.rf_host_to_device(u)
    got = np.asarray(K.rf_probe(dev, pcol))
    assert (got == np.isin(np.arange(16), [1, 2, 3, 5, 8])).all()
    dom = K.rf_host_to_device(big)
    assert dom["kind"] == "domain"
    assert np.asarray(K.rf_probe(dom, pcol)).all()


def test_merge_static_in_list_with_runtime_minmax():
    """ISSUE-5 satellite: runtime-derived domains INTERSECT statically
    extracted ones — an IN-list static domain combined with a runtime
    min/max on the same column keeps only the in-range list values."""
    static = {"l_partkey": Domain(values=[2, 40, 700]),
              "l_shipdate": Domain(10, 20)}
    runtime = {"l_partkey": Domain(30, 800), "l_orderkey": Domain(1, 5)}
    merged = merge_domain_maps(static, runtime)
    assert merged["l_partkey"].values == [40, 700]
    assert (merged["l_shipdate"].lo, merged["l_shipdate"].hi) == (10, 20)
    assert (merged["l_orderkey"].lo, merged["l_orderkey"].hi) == (1, 5)
    # intersection semantics drive pruning: a stripe overlapping the
    # static list but not the runtime range is now prunable
    assert not merged["l_partkey"].overlaps(0, 29)
    assert merged["l_partkey"].overlaps(30, 50)


# ---------------------------------------------------------------------------
# planner annotation
# ---------------------------------------------------------------------------


def test_planner_annotates_q17(tpch_catalog_tiny):
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.plan import nodes as P
    from presto_tpu.sql.parser import parse

    session = presto_tpu.connect(tpch_catalog_tiny)
    plan = plan_statement(session, parse(QUERIES[17]))
    produced, consumed = [], []

    def walk(n, seen):
        if id(n) in seen:
            return
        seen.add(id(n))
        produced.extend(getattr(n, "rf_produce", None) or [])
        if isinstance(n, P.TableScan):
            consumed.extend(getattr(n, "rf_consume", None) or [])
        for s in n.sources:
            walk(s, seen)

    seen = set()
    walk(plan.root, seen)
    for sub in plan.subplans.values():
        walk(sub, seen)
    assert produced, "q17's selective part join produced no filter"
    fids = {s["fid"] for s in produced}
    hit = [c for c in consumed if c["fid"] in fids]
    assert hit and hit[0]["column"] == "l_partkey", consumed


def test_planner_kill_switch(tpch_catalog_tiny):
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.sql.parser import parse

    session = presto_tpu.connect(tpch_catalog_tiny,
                                 dynamic_filtering=False)
    plan = plan_statement(session, parse(QUERIES[17]))

    def any_rf(n, seen):
        if id(n) in seen:
            return False
        seen.add(id(n))
        if getattr(n, "rf_produce", None) or getattr(n, "rf_consume", None):
            return True
        return any(any_rf(s, seen) for s in n.sources)

    assert not any_rf(plan.root, set())


def test_resolve_probe_refuses_shared_subtrees():
    from presto_tpu.plan import nodes as P

    scan = P.TableScan("t", {"a": "a"}, {"a": T.BIGINT})
    scan.shared_subtree = True
    assert RF.resolve_probe_scan(scan, "a") is None


# ---------------------------------------------------------------------------
# engine equivalence: q17-class on vs off
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def dyn_sessions(tpch_catalog_tiny):
    on = presto_tpu.connect(tpch_catalog_tiny, execution_mode="dynamic")
    off = presto_tpu.connect(tpch_catalog_tiny, execution_mode="dynamic",
                             dynamic_filtering=False)
    return on, off


def test_q17_dynamic_rows_pruned_and_identical(dyn_sessions):
    """Acceptance: with dynamic filtering on, q17 prunes probe rows
    BEFORE the join (df_rows_pruned > 0) and the result checksum is
    identical to dynamic_filtering=off."""
    on, off = dyn_sessions
    r_on = on.sql(QUERIES[17])
    r_off = off.sql(QUERIES[17])
    assert norm(r_on.rows) == norm(r_off.rows)
    assert r_on.stats.df_filters_produced >= 1
    assert r_on.stats.df_filters_applied >= 1
    assert r_on.stats.df_rows_pruned > 0
    assert r_off.stats.df_filters_applied == 0
    assert r_off.stats.df_rows_pruned == 0


@pytest.mark.slow
@pytest.mark.parametrize("qid", [8, 19])
def test_q8_q19_dynamic_identical(dyn_sessions, qid):
    on, off = dyn_sessions
    assert norm(on.sql(QUERIES[qid]).rows) == norm(off.sql(QUERIES[qid]).rows)


def test_q17_compiled_on_off_identical(tpch_catalog_tiny):
    """Compiled mode: the filter is built and probed INSIDE the traced
    program (trace-time df counters), results identical on/off."""
    on = presto_tpu.connect(tpch_catalog_tiny, execution_mode="compiled")
    off = presto_tpu.connect(tpch_catalog_tiny, execution_mode="compiled",
                             dynamic_filtering=False)
    r_on = on.sql(QUERIES[17])
    r_off = off.sql(QUERIES[17])
    assert norm(r_on.rows) == norm(r_off.rows)
    assert r_on.stats.execution_mode == "compiled"
    assert r_on.stats.df_filters_applied >= 1
    assert r_off.stats.df_filters_applied == 0


# ---------------------------------------------------------------------------
# chunked mode: chunk pruning + equivalence
# ---------------------------------------------------------------------------


def _chunked_session(cat, df=True):
    s = presto_tpu.connect(cat)
    s.properties["chunked_rows_threshold"] = 10_000
    s.properties["chunk_orders"] = 4_000  # ~4 chunks at SF0.01
    s.properties["dynamic_filtering"] = df
    return s


def test_chunked_runtime_domain_prunes_chunks(tpch_catalog_tiny):
    """Acceptance (chunked): a resident build joined to the chunked
    probe on the bucket column skips every chunk whose orderkey range
    misses the runtime domain — df_chunks_pruned > 0, results identical
    to filtering off AND to whole-table execution."""
    ddl = ("CREATE TABLE ok_list AS SELECT o_orderkey AS k FROM orders "
           "WHERE o_orderkey < 2000")
    q = ("SELECT count(*) c, sum(l_quantity) q FROM lineitem, ok_list "
         "WHERE l_orderkey = k")
    s_on = _chunked_session(tpch_catalog_tiny, True)
    s_off = _chunked_session(tpch_catalog_tiny, False)
    whole = presto_tpu.connect(tpch_catalog_tiny)
    whole.sql(ddl)  # the catalog is shared: create once
    r_on = s_on.sql(q)
    r_off = s_off.sql(q)
    r_whole = whole.sql(q)
    try:
        assert norm(r_on.rows) == norm(r_off.rows) == norm(r_whole.rows)
        assert r_on.stats.execution_mode == "chunked"
        assert r_on.stats.df_chunks_pruned > 0
        assert r_on.stats.df_filters_applied >= 1
        assert r_off.stats.df_chunks_pruned == 0
    finally:
        whole.sql("DROP TABLE ok_list")


@pytest.mark.slow
def test_chunked_q17_on_off_identical(tpch_catalog_tiny):
    """q17 chunked: the in-trace filter applies (trace counter), results
    identical.  Chunk pruning is honestly 0 here — l_partkey does not
    correlate with the orderkey-range chunk grid (docs/PERF.md r10)."""
    s_on = _chunked_session(tpch_catalog_tiny, True)
    s_off = _chunked_session(tpch_catalog_tiny, False)
    r_on = s_on.sql(QUERIES[17])
    r_off = s_off.sql(QUERIES[17])
    assert r_on.stats.execution_mode == "chunked"
    assert norm(r_on.rows) == norm(r_off.rows)
    assert r_on.stats.df_filters_applied >= 1


# ---------------------------------------------------------------------------
# cluster mode: in-fragment filters + the coordinator-routed side channel
# ---------------------------------------------------------------------------


CLUSTER_Q = ("SELECT count(*) c, sum(l_extendedprice) s FROM lineitem, "
             "part WHERE p_partkey = l_partkey "
             "AND p_container = 'MED BOX'")


def _worker_counters(url):
    from presto_tpu.parallel import cluster as C

    req = C._signed_request("GET", f"{url}/v1/info")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())["counters"]


@pytest.fixture(scope="module")
def df_cluster(tpch_catalog_tiny):
    from presto_tpu.parallel import cluster as C

    session = presto_tpu.connect(tpch_catalog_tiny)
    workers = [C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache").start()
               for _ in range(2)]
    cs = C.ClusterSession(session, [w.url for w in workers])
    yield session, cs, workers
    for w in workers:
        if not w.crashed:
            w.stop()


def _df_delta(workers, before):
    keys = ("df_filters_produced", "df_filters_applied", "df_rows_pruned")
    after = [_worker_counters(w.url) for w in workers]
    return {k: sum(a[k] - b[k] for a, b in zip(after, before))
            for k in keys}


@pytest.mark.slow
def test_cluster_broadcast_filters_in_fragment(df_cluster):
    """Default (broadcast build): the probe fragment holds both the
    producer join and the probe scan — workers apply the filter locally
    and report it via /v1/info; results match single-device."""
    session, cs, workers = df_cluster
    want = norm(session.sql(CLUSTER_Q).rows)
    before = [_worker_counters(w.url) for w in workers]
    got = cs.sql(CLUSTER_Q)
    assert norm(got.rows) == want
    d = _df_delta(workers, before)
    assert d["df_filters_applied"] >= 1, d
    assert d["df_rows_pruned"] > 0, d


@pytest.mark.slow
def test_cluster_partitioned_side_channel(df_cluster):
    """Partitioned join (broadcast threshold 0): the probe leaf fragment
    is separate from the join fragment, so filters travel the side
    channel — each join task POSTs its repartition bucket's partial
    summary to the probe tasks, which wait (dynamic_filtering_wait_ms)
    and union the parts.  Probe rows prune on the workers; results
    identical."""
    session, cs, workers = df_cluster
    want = norm(session.sql(CLUSTER_Q).rows)
    session.set("broadcast_join_threshold_rows", 0)
    session.set("dynamic_filtering_wait_ms", 8000)
    before = [_worker_counters(w.url) for w in workers]
    try:
        got = cs.sql(CLUSTER_Q)
    finally:
        session.set("broadcast_join_threshold_rows", 1_000_000)
        session.set("dynamic_filtering_wait_ms", 0)
    assert norm(got.rows) == want
    d = _df_delta(workers, before)
    assert d["df_filters_applied"] >= 1, d
    assert d["df_rows_pruned"] > 0, d
    after = [_worker_counters(w.url) for w in workers]
    assert any(a["df_wait_ms"] > 0 for a in after)


@pytest.mark.slow
def test_cluster_kill_switch_no_activity(df_cluster):
    session, cs, workers = df_cluster
    want = norm(session.sql(CLUSTER_Q).rows)
    session.set("dynamic_filtering", False)
    before = [_worker_counters(w.url) for w in workers]
    try:
        got = cs.sql(CLUSTER_Q)
    finally:
        session.set("dynamic_filtering", True)
    assert norm(got.rows) == want
    d = _df_delta(workers, before)
    assert d == {"df_filters_produced": 0, "df_filters_applied": 0,
                 "df_rows_pruned": 0}, d
