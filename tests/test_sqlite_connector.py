"""External-database connector over SQLite (reference: presto-base-jdbc
BaseJdbcClient + the mysql/postgresql connectors built on it)."""

import sqlite3

import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.catalog import Catalog
from presto_tpu.connectors.sqlite import attach_sqlite


@pytest.fixture()
def db(tmp_path):
    path = str(tmp_path / "ext.db")
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE emp (id INTEGER, name TEXT, salary REAL, "
                 "dept_id INTEGER)")
    conn.execute("CREATE TABLE dept (dept_id INTEGER, dept_name TEXT)")
    conn.executemany("INSERT INTO emp VALUES (?, ?, ?, ?)", [
        (1, "alice", 120.5, 10), (2, "bob", 95.0, 20),
        (3, "carol", 130.0, 10), (4, "dave", None, 20),
    ])
    conn.executemany("INSERT INTO dept VALUES (?, ?)",
                     [(10, "eng"), (20, "sales")])
    conn.commit()
    conn.close()
    return path


def test_discovery_and_scan(db):
    cat = Catalog()
    names = attach_sqlite(cat, db)
    assert "sqlite.emp" in names and "sqlite.dept" in names
    s = presto_tpu.connect(cat)
    assert s.sql("SELECT count(*) FROM emp").rows == [(4,)]
    r = s.sql("SELECT name, salary FROM sqlite.emp "
              "WHERE salary > 100 ORDER BY name").rows
    assert r == [("alice", 120.5), ("carol", 130.0)]


def test_join_external_with_internal(db):
    cat = Catalog()
    attach_sqlite(cat, db)
    s = presto_tpu.connect(cat)
    r = s.sql("SELECT dept_name, count(*) c, sum(salary) FROM emp, dept "
              "WHERE emp.dept_id = dept.dept_id GROUP BY dept_name "
              "ORDER BY dept_name").rows
    assert r[0][0] == "eng" and r[0][1] == 2 and abs(r[0][2] - 250.5) < 1e-9
    assert r[1][0] == "sales" and r[1][1] == 2
    # CTAS from the external table into the in-memory connector
    s.sql("CREATE TABLE local_copy AS SELECT id, name FROM sqlite.emp")
    assert s.sql("SELECT count(*) FROM local_copy").rows == [(4,)]


def test_splits_and_stats(db):
    cat = Catalog()
    attach_sqlite(cat, db)
    t = cat.get("sqlite.emp")
    ranges = t.splits(2)
    assert len(ranges) == 2
    total = sum(len(t.read(["id"], split=r)["id"]) for r in ranges)
    assert total == 4
    st = t.column_stats("id")
    assert st.min == 1.0 and st.max == 4.0 and st.ndv == 4
    assert t.column_stats("name").ndv == 4
