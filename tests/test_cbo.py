"""Cost-based optimizer tests: filter selectivity, join cardinality,
and cost-driven join ordering (reference analogs: TestFilterStatsCalculator,
TestJoinStatsRule, TestReorderJoins in presto-main)."""

import numpy as np
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.catalog import Catalog, MemoryTable
from presto_tpu.plan import stats as S
from presto_tpu.plan.ir import Call, Lit, Ref
from presto_tpu.types import BOOLEAN, BIGINT


def _scan_stats(rows, ndv, lo, hi):
    cols = {"k": S.ColStats(min=lo, max=hi, ndv=ndv)}
    return S.NodeStats(rows, cols, [], {})


def test_range_selectivity_narrows():
    src = _scan_stats(1000, 100, 0.0, 100.0)
    pred = Call("lt", (Ref("k", BIGINT), Lit(25, BIGINT)), BOOLEAN)
    sel, cols = S.filter_selectivity(src, pred)
    assert abs(sel - 0.25) < 1e-9
    assert cols["k"].max == 25
    # ndv must NOT be narrowed: it feeds static capacity sizing, which
    # needs upper bounds (estimates cap ndv by est_rows separately)
    assert cols["k"].ndv == 100


def test_eq_selectivity_uses_ndv():
    src = _scan_stats(1000, 50, 0.0, 100.0)
    pred = Call("eq", (Ref("k", BIGINT), Lit(7, BIGINT)), BOOLEAN)
    sel, _ = S.filter_selectivity(src, pred)
    assert abs(sel - 1.0 / 50) < 1e-9


def test_or_and_not_combinators():
    src = _scan_stats(1000, 10, 0.0, 10.0)
    eq = Call("eq", (Ref("k", BIGINT), Lit(1, BIGINT)), BOOLEAN)
    or_ = Call("or", (eq, eq), BOOLEAN)
    sel, _ = S.filter_selectivity(src, or_)
    assert abs(sel - (0.1 + 0.1 - 0.01)) < 1e-9
    not_ = Call("not", (eq,), BOOLEAN)
    sel, _ = S.filter_selectivity(src, not_)
    assert abs(sel - 0.9) < 1e-9


def test_join_cardinality_formula():
    l = _scan_stats(10_000, 100, 0, 100)
    r = _scan_stats(500, 100, 0, 100)
    est = S.join_cardinality(l, r, [("k", "k")])
    assert abs(est - 10_000 * 500 / 100) < 1e-6


@pytest.fixture()
def skew_catalog():
    """Two candidate build tables joined to one fact table: `big_dim` is
    larger than `small_dim` unfiltered, but a selective filter makes the
    filtered big_dim the better first join.  Row-count-greedy ordering
    picks small_dim first; cost-based ordering must pick big_dim."""
    rng = np.random.default_rng(42)
    n_fact = 20_000
    cat = Catalog()
    cat.register(MemoryTable(
        "fact",
        {"f_id": T.BIGINT, "f_big": T.BIGINT, "f_small": T.BIGINT},
        {"f_id": np.arange(n_fact),
         "f_big": rng.integers(0, 5000, n_fact),
         "f_small": rng.integers(0, 1000, n_fact)}))
    cat.register(MemoryTable(
        "big_dim", {"b_id": T.BIGINT, "b_sel": T.BIGINT},
        {"b_id": np.arange(5000), "b_sel": np.arange(5000) % 500}))
    cat.register(MemoryTable(
        "small_dim", {"s_id": T.BIGINT, "s_val": T.BIGINT},
        {"s_id": np.arange(1000), "s_val": np.arange(1000)}))
    return cat


def test_cost_based_join_order(skew_catalog):
    s = presto_tpu.connect(skew_catalog)
    sql = """
      SELECT count(*) FROM fact, big_dim, small_dim
      WHERE f_big = b_id AND f_small = s_id AND b_sel = 0
    """
    txt = s.sql("EXPLAIN " + sql).rows[0][0]
    # the selective big_dim join must appear BELOW (after in text) the
    # small_dim join in the left-deep tree: deepest join binds first
    pos_b = txt.find("b_id")
    pos_s = txt.find("s_id")
    assert pos_b > 0 and pos_s > 0
    assert pos_b > pos_s, f"filtered big_dim should join first:\n{txt}"
    # estimates rendered in EXPLAIN
    assert "{rows:" in txt
    # and the query still returns the right answer
    n = s.sql(sql).rows[0][0]
    oracle = 0
    fact = skew_catalog.get("fact").data
    sel = (fact["f_big"] % 500) == 0  # b_sel = b_id % 500
    oracle = int(sel.sum())
    assert n == oracle


def test_tpch_q3_order_unchanged_and_correct(tpch_catalog_tiny, tpch_sqlite_tiny):
    from tests.sqlite_oracle import assert_same_results, to_sqlite
    from tests.tpch_queries import QUERIES

    s = presto_tpu.connect(tpch_catalog_tiny)
    for qid in (3, 5, 9, 10):
        rows = s.sql(QUERIES[qid]).rows
        expected = tpch_sqlite_tiny.execute(to_sqlite(QUERIES[qid])).fetchall()
        assert_same_results(rows, expected, ordered=True)
