"""Pallas kernel + float-key tests (CPU interpreter path; the TPU
compiled path is exercised by bench.py on hardware)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from presto_tpu.exec import kernels as K


def test_fused_group_sums_matches_segment_sum():
    rng = np.random.default_rng(0)
    n, k, G = 120_000, 6, 17
    vals = jnp.asarray(rng.random((k, n)) * 1e4)
    gid = jnp.asarray(rng.integers(0, G, n).astype(np.int32))
    out = K.fused_group_sums(vals, gid, G)
    ref = np.stack([jax.ops.segment_sum(vals[i], gid, num_segments=G)
                    for i in range(k)])
    assert np.allclose(np.asarray(out), ref, rtol=1e-9)


def test_fused_group_sums_f32_inputs():
    rng = np.random.default_rng(1)
    n, G = 100_000, 8
    vals = jnp.asarray(rng.random((2, n)), dtype=jnp.float32)
    gid = jnp.asarray(rng.integers(0, G, n).astype(np.int32))
    out = K.fused_group_sums(vals, gid, G)
    assert out.dtype == jnp.float64 or out.dtype == jnp.float32
    ref = np.stack([jax.ops.segment_sum(vals[i].astype(jnp.float64), gid,
                                        num_segments=G) for i in range(2)])
    assert np.allclose(np.asarray(out, dtype=np.float64), ref, rtol=1e-5)


def _check_orderable(fn, vals):
    r = np.asarray(jax.jit(fn)(jnp.asarray(vals)))
    finite = np.isfinite(vals)
    o = np.argsort(vals[finite], kind="stable")
    k = r[finite][o]
    assert (k[1:] >= k[:-1]).all(), "not monotone"  # diff would wrap int64
    i_nan = np.where(np.isnan(vals))[0]
    i_inf = np.where(np.isposinf(vals))[0]
    i_ninf = np.where(np.isneginf(vals))[0]
    if len(i_nan) and len(i_inf):
        assert r[i_nan[0]] > r[i_inf[0]] >= k.max()
    if len(i_ninf):
        assert r[i_ninf[0]] <= k.min()
    # +-0 equal
    z = np.asarray(jax.jit(fn)(jnp.asarray([0.0, -0.0])))
    assert z[0] == z[1] == 0
    return r


VALS = None


def _vals():
    global VALS
    if VALS is None:
        rng = np.random.default_rng(3)
        VALS = np.concatenate([
            rng.standard_normal(100_000) * 10.0 ** rng.integers(-300, 300, 100_000),
            np.array([0.0, -0.0, np.inf, -np.inf, np.nan, 1.0, 2.0, 0.5,
                      np.nextafter(1.0, 2.0), np.nextafter(1.0, 0.0)]),
            np.round(rng.random(50_000) * 1e7) / 100.0,
        ])
    return VALS


def test_orderable_top_binade():
    # 2^-1023 is subnormal: a naive one-step scale collapses the whole
    # top binade (review finding); sentinels must stay above DBL_MAX
    vals = np.array([8.98e307, 9e307, 1e308, 1.5e308,
                     1.7976931348623157e308, -1.7976931348623157e308,
                     2.0 ** 1022, 2.0 ** 1023, np.inf, -np.inf, np.nan])
    r = np.asarray(jax.jit(K._f64_orderable_arith)(jnp.asarray(vals)))
    finite = np.isfinite(vals)
    k = r[finite][np.argsort(vals[finite])]
    # compare, don't diff: int64 differences of near-full-range keys wrap
    assert (k[1:] > k[:-1]).all()
    imax = np.iinfo(np.int64).max
    assert k.max() < imax - 16  # below the inf sentinel and row mask
    assert r[8] == imax - 16 and r[10] == imax - 8 and r[9] == -(imax - 16)


def test_orderable_arith_exact():
    vals = _vals()
    r = _check_orderable(K._f64_orderable_arith, vals)
    # exact path: injective on normal-range values
    nz = np.isfinite(vals) & (np.abs(vals) >= 2.2250738585072014e-308)
    assert len(np.unique(vals[nz])) == len(np.unique(r[nz]))


def test_orderable_pair_monotone():
    vals = _vals()
    r = _check_orderable(K._f64_orderable_pair, vals)
    # pair path: injective at >= 48-bit granularity (money values)
    money = np.round(np.random.default_rng(4).random(50_000) * 1e7) / 100.0
    rm = np.asarray(jax.jit(K._f64_orderable_pair)(jnp.asarray(money)))
    assert len(np.unique(money)) == len(np.unique(rm))


def test_fused_agg_in_query(tpch_catalog_tiny):
    import presto_tpu

    s = presto_tpu.connect(tpch_catalog_tiny)
    on = s.sql("SELECT l_returnflag, count(*), sum(l_extendedprice), "
               "avg(l_quantity) FROM lineitem GROUP BY l_returnflag "
               "ORDER BY 1").rows
    s2 = presto_tpu.connect(tpch_catalog_tiny)
    s2.set("pallas_fused_agg", False)
    off = s2.sql("SELECT l_returnflag, count(*), sum(l_extendedprice), "
                 "avg(l_quantity) FROM lineitem GROUP BY l_returnflag "
                 "ORDER BY 1").rows
    assert len(on) == len(off)
    for a, b in zip(on, off):
        assert a[0] == b[0] and a[1] == b[1]
        assert abs(a[2] - b[2]) < 1e-6 * abs(b[2])
        assert abs(a[3] - b[3]) < 1e-9 * abs(b[3])
