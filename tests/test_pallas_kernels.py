"""Pallas kernel + float-key tests (CPU interpreter path; the TPU
compiled path is exercised by bench.py on hardware)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from presto_tpu.exec import gather as G
from presto_tpu.exec import kernels as K


def test_fused_group_sums_matches_segment_sum():
    rng = np.random.default_rng(0)
    n, k, G = 120_000, 6, 17
    vals = jnp.asarray(rng.random((k, n)) * 1e4)
    gid = jnp.asarray(rng.integers(0, G, n).astype(np.int32))
    out = K.fused_group_sums(vals, gid, G)
    ref = np.stack([jax.ops.segment_sum(vals[i], gid, num_segments=G)
                    for i in range(k)])
    assert np.allclose(np.asarray(out), ref, rtol=1e-9)


def test_fused_group_sums_f32_inputs():
    rng = np.random.default_rng(1)
    n, G = 100_000, 8
    vals = jnp.asarray(rng.random((2, n)), dtype=jnp.float32)
    gid = jnp.asarray(rng.integers(0, G, n).astype(np.int32))
    out = K.fused_group_sums(vals, gid, G)
    assert out.dtype == jnp.float64 or out.dtype == jnp.float32
    ref = np.stack([jax.ops.segment_sum(vals[i].astype(jnp.float64), gid,
                                        num_segments=G) for i in range(2)])
    assert np.allclose(np.asarray(out, dtype=np.float64), ref, rtol=1e-5)


def _check_orderable(fn, vals):
    r = np.asarray(jax.jit(fn)(jnp.asarray(vals)))
    finite = np.isfinite(vals)
    o = np.argsort(vals[finite], kind="stable")
    k = r[finite][o]
    assert (k[1:] >= k[:-1]).all(), "not monotone"  # diff would wrap int64
    i_nan = np.where(np.isnan(vals))[0]
    i_inf = np.where(np.isposinf(vals))[0]
    i_ninf = np.where(np.isneginf(vals))[0]
    if len(i_nan) and len(i_inf):
        assert r[i_nan[0]] > r[i_inf[0]] >= k.max()
    if len(i_ninf):
        assert r[i_ninf[0]] <= k.min()
    # +-0 equal
    z = np.asarray(jax.jit(fn)(jnp.asarray([0.0, -0.0])))
    assert z[0] == z[1] == 0
    return r


VALS = None


def _vals():
    global VALS
    if VALS is None:
        rng = np.random.default_rng(3)
        VALS = np.concatenate([
            rng.standard_normal(100_000) * 10.0 ** rng.integers(-300, 300, 100_000),
            np.array([0.0, -0.0, np.inf, -np.inf, np.nan, 1.0, 2.0, 0.5,
                      np.nextafter(1.0, 2.0), np.nextafter(1.0, 0.0)]),
            np.round(rng.random(50_000) * 1e7) / 100.0,
        ])
    return VALS


def test_orderable_top_binade():
    # 2^-1023 is subnormal: a naive one-step scale collapses the whole
    # top binade (review finding); sentinels must stay above DBL_MAX
    vals = np.array([8.98e307, 9e307, 1e308, 1.5e308,
                     1.7976931348623157e308, -1.7976931348623157e308,
                     2.0 ** 1022, 2.0 ** 1023, np.inf, -np.inf, np.nan])
    r = np.asarray(jax.jit(K._f64_orderable_arith)(jnp.asarray(vals)))
    finite = np.isfinite(vals)
    k = r[finite][np.argsort(vals[finite])]
    # compare, don't diff: int64 differences of near-full-range keys wrap
    assert (k[1:] > k[:-1]).all()
    imax = np.iinfo(np.int64).max
    assert k.max() < imax - 16  # below the inf sentinel and row mask
    assert r[8] == imax - 16 and r[10] == imax - 8 and r[9] == -(imax - 16)


def test_orderable_arith_exact():
    vals = _vals()
    r = _check_orderable(K._f64_orderable_arith, vals)
    # exact path: injective on normal-range values
    nz = np.isfinite(vals) & (np.abs(vals) >= 2.2250738585072014e-308)
    assert len(np.unique(vals[nz])) == len(np.unique(r[nz]))


def test_orderable_pair_monotone():
    vals = _vals()
    r = _check_orderable(K._f64_orderable_pair, vals)
    # pair path: injective at >= 48-bit granularity (money values)
    money = np.round(np.random.default_rng(4).random(50_000) * 1e7) / 100.0
    rm = np.asarray(jax.jit(K._f64_orderable_pair)(jnp.asarray(money)))
    assert len(np.unique(money)) == len(np.unique(rm))


def test_fused_agg_in_query(tpch_catalog_tiny):
    import presto_tpu

    s = presto_tpu.connect(tpch_catalog_tiny)
    on = s.sql("SELECT l_returnflag, count(*), sum(l_extendedprice), "
               "avg(l_quantity) FROM lineitem GROUP BY l_returnflag "
               "ORDER BY 1").rows
    s2 = presto_tpu.connect(tpch_catalog_tiny)
    s2.set("pallas_fused_agg", False)
    off = s2.sql("SELECT l_returnflag, count(*), sum(l_extendedprice), "
                 "avg(l_quantity) FROM lineitem GROUP BY l_returnflag "
                 "ORDER BY 1").rows
    assert len(on) == len(off)
    for a, b in zip(on, off):
        assert a[0] == b[0] and a[1] == b[1]
        assert abs(a[2] - b[2]) < 1e-6 * abs(b[2])
        assert abs(a[3] - b[3]) < 1e-9 * abs(b[3])


# ---------------------------------------------------------------------------
# gather-aware tier (exec/gather.py): blocked Pallas gather + sort-order
# staging must be BYTE-IDENTICAL to the flat packed gather
# ---------------------------------------------------------------------------


@pytest.fixture
def tiny_gather(monkeypatch):
    """Shrink the routing/window constants so the staged tier (and the
    Pallas block-gather inside it) engages at test sizes; 'force'
    opts in to staging off-TPU (auto mode is TPU-only)."""
    monkeypatch.setenv("PRESTO_TPU_GATHER", "force")
    monkeypatch.setattr(G, "_STAGED_MIN_INDICES", 1)
    monkeypatch.setattr(G, "_IB", 64)
    monkeypatch.setattr(G, "_MAX_WINDOW", 512)
    yield


def _dtype_arrays(n, rng):
    """One array per engine dtype class take_rows packs differently."""
    return [
        jnp.asarray(rng.integers(-(1 << 30), 1 << 30, n).astype(np.int32)),
        jnp.asarray(rng.random(n).astype(np.float32)),
        jnp.asarray(rng.integers(-(1 << 60), 1 << 60, n)),      # i64 pair
        jnp.asarray(rng.random(n)),                             # f64 direct
        jnp.asarray(rng.integers(0, 2, n).astype(bool)),
        jnp.asarray(rng.integers(-100, 100, n).astype(np.int16)),
    ]


def test_staged_take_rows_matches_flat(tiny_gather, monkeypatch):
    rng = np.random.default_rng(7)
    n, m = 5000, 4096
    arrays = _dtype_arrays(n, rng)
    idx = jnp.asarray(rng.integers(0, n, m).astype(np.int32))
    assert G.gather_route(n, m, 8) == "staged"
    staged = K.take_rows(arrays, idx)
    monkeypatch.setenv("PRESTO_TPU_GATHER", "flat")
    flat = K.take_rows(arrays, idx)
    for a, b in zip(flat, staged):
        assert a.dtype == b.dtype
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_staged_take_rows_presorted(tiny_gather, monkeypatch):
    rng = np.random.default_rng(8)
    n, m = 3000, 2048
    arrays = _dtype_arrays(n, rng)
    sidx = jnp.asarray(np.sort(rng.integers(0, n, m)).astype(np.int32))
    staged = K.take_rows(arrays, sidx, presorted=True)
    monkeypatch.setenv("PRESTO_TPU_GATHER", "flat")
    flat = K.take_rows(arrays, sidx)
    for a, b in zip(flat, staged):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_staged_gather_skew_falls_back_covered(tiny_gather):
    """Index blocks whose span exceeds the window must take the
    lax.cond fallback and still return exact rows."""
    rng = np.random.default_rng(9)
    n, m = 8192, 1024
    src = jnp.asarray(rng.integers(0, 1 << 32, (n, 3)).astype(np.uint32))
    # maximally skewed: indices alternate across the whole range
    skew = np.sort(np.concatenate([
        np.zeros(m // 2, np.int32), np.full(m - m // 2, n - 1, np.int32)]))
    # interleave so single blocks span the full source
    skew[::2], skew[1::2] = 0, n - 1
    skew = np.sort(skew)  # staged_gather requires ascending
    out = G.staged_gather(src, jnp.asarray(skew))
    assert np.array_equal(np.asarray(out), np.asarray(src)[skew])


def test_staged_gather_dense_uses_windows(tiny_gather):
    """Dense ascending indices satisfy coverage (windows engage) and
    the result is exact."""
    rng = np.random.default_rng(10)
    n, m = 4096, 4096
    src = jnp.asarray(rng.integers(0, 1 << 32, (n, 2)).astype(np.uint32))
    sidx = jnp.asarray(np.sort(rng.integers(0, n, m)).astype(np.int32))
    W = G.window_rows(n, m)
    assert W is not None
    out = G.staged_gather(src, sidx)
    assert np.array_equal(np.asarray(out), np.asarray(src)[np.asarray(sidx)])


def test_gather_batch_staged_oob_and_validity(tiny_gather, monkeypatch):
    """gather_batch clips out-of-range indices and ANDs idx_valid the
    same way on both routes, across validity masks."""
    from presto_tpu import types as T
    from presto_tpu.batch import Batch, Column

    rng = np.random.default_rng(11)
    n, m = 2000, 2048
    cols = {
        "a": Column(jnp.asarray(rng.integers(0, 99, n).astype(np.int32)),
                    jnp.asarray(rng.integers(0, 2, n).astype(bool)),
                    T.INTEGER, None),
        "b": Column(jnp.asarray(rng.random(n)), None, T.DOUBLE, None),
    }
    b = Batch(cols, jnp.asarray(rng.integers(0, 2, n).astype(bool)))
    idx = jnp.asarray(rng.integers(-50, n + 50, m).astype(np.int32))
    iv = jnp.asarray(rng.integers(0, 2, m).astype(bool))
    staged = K.gather_batch(b, idx, idx_valid=iv)
    monkeypatch.setenv("PRESTO_TPU_GATHER", "flat")
    flat = K.gather_batch(b, idx, idx_valid=iv)
    assert np.array_equal(np.asarray(staged.sel), np.asarray(flat.sel))
    for name in cols:
        sc, fc = staged.columns[name], flat.columns[name]
        assert np.array_equal(np.asarray(sc.data), np.asarray(fc.data))
        if fc.valid is not None:
            assert np.array_equal(np.asarray(sc.valid), np.asarray(fc.valid))


def test_staged_gather_empty_inputs(tiny_gather):
    src = jnp.zeros((0, 2), jnp.uint32)
    out = G.staged_gather(jnp.zeros((16, 2), jnp.uint32),
                          jnp.zeros((0,), jnp.int32))
    assert out.shape == (0, 2)
    # empty SOURCE goes through take_rows' zero-fill early return
    zero = K.take_rows([jnp.zeros((0,), jnp.int32)],
                       jnp.asarray([0, 0], dtype=jnp.int32))
    assert zero[0].shape == (2,)


def test_sort_order_plan_keeps_alignment():
    rng = np.random.default_rng(12)
    m = 5000
    idx = jnp.asarray(rng.integers(0, 1000, m).astype(np.int32))
    a = jnp.asarray(rng.integers(0, 7, m))
    flag = jnp.asarray(rng.integers(0, 2, m).astype(bool))
    sidx, (a2, f2) = K.sort_order_plan(idx, a, flag)
    assert (np.diff(np.asarray(sidx)) >= 0).all()
    assert f2.dtype == jnp.bool_
    before = sorted(zip(np.asarray(idx).tolist(), np.asarray(a).tolist(),
                        np.asarray(flag).tolist()))
    after = sorted(zip(np.asarray(sidx).tolist(), np.asarray(a2).tolist(),
                       np.asarray(f2).tolist()))
    assert before == after


# ---- routing heuristics (size/width crossover) ----------------------------


def test_gather_route_crossovers(monkeypatch):
    monkeypatch.setenv("PRESTO_TPU_GATHER", "force")
    M = G._STAGED_MIN_INDICES
    # large + wide: staged, both orders
    assert G.gather_route(1 << 23, M, 4) == "staged"
    assert G.gather_route(1 << 23, M, 4, presorted=True) == "staged"
    # below the index threshold: flat
    assert G.gather_route(1 << 23, M - 1, 8) == "flat"
    # narrow request-order gathers can't amortize the co-sort home...
    assert G.gather_route(1 << 23, M, 1) == "flat"
    # ...but presorted ones skip it, so width 1 still stages
    assert G.gather_route(1 << 23, M, 1, presorted=True) == "staged"
    # degenerate sources
    assert G.gather_route(0, M, 4) == "flat"
    assert G.gather_route(1 << 23, M, 0) == "flat"


def test_gather_route_env_off(monkeypatch):
    monkeypatch.setenv("PRESTO_TPU_GATHER", "flat")
    assert G.gather_route(1 << 23, 1 << 22, 8) == "flat"
    assert not G.sort_order_worthwhile(1 << 22, 4)


def test_gather_route_auto_is_tpu_only(monkeypatch):
    """Auto mode must NOT stage off-TPU: the interpret-mode Pallas
    grid at production index counts unrolls into an XLA CPU program
    that effectively never finishes compiling (tpcds q37 regression)."""
    monkeypatch.delenv("PRESTO_TPU_GATHER", raising=False)
    assert jax.default_backend() != "tpu"
    assert G.gather_route(1 << 23, 1 << 22, 8) == "flat"
    assert G.gather_route(1 << 23, 1 << 22, 8, presorted=True) == "flat"
    assert not G.sort_order_worthwhile(1 << 22, 4)


def test_window_rows_density():
    IB = G._IB
    # dense (m == n): the 2x slack window
    assert G.window_rows(1 << 23, 1 << 23) == 2 * IB
    # 2:1 density doubles the window (2x slack x 2 rows/index)
    assert G.window_rows(1 << 23, 1 << 22) == 4 * IB
    # too sparse for any window: staging falls back to the plain
    # ascending gather
    assert G.window_rows(1 << 23, 1 << 18) is None
    assert G.window_rows(0, 1 << 20) is None


def test_sort_order_worthwhile_gate(monkeypatch):
    monkeypatch.setenv("PRESTO_TPU_GATHER", "force")
    M = G._STAGED_MIN_INDICES
    assert G.sort_order_worthwhile(M, 3)
    assert not G.sort_order_worthwhile(M - 1, 3)  # too small
    assert not G.sort_order_worthwhile(M, 0)      # build not wider
    assert not G.sort_order_worthwhile(M, -2)


def test_batch_word_width():
    from presto_tpu import types as T
    from presto_tpu.batch import Batch, Column

    n = 8
    b = Batch({
        "i": Column(jnp.zeros((n,), jnp.int32), None, T.INTEGER, None),
        "l": Column(jnp.zeros((n,), jnp.int64),
                    jnp.ones((n,), bool), T.BIGINT, None),
        "d": Column(jnp.zeros((n,), jnp.float64), None, T.DOUBLE, None),
    }, jnp.ones((n,), bool))
    # i32=1, i64+valid=3, f64=2
    assert K.batch_word_width(b) == 6


def test_expanding_join_sort_order_materialization(tiny_gather):
    """One-to-many join whose build side is WIDER than the probe, under
    an order-insensitive consumer: the executor pre-permutes the
    expansion into build-index order (sort_order_plan) and gathers the
    wide side presorted.  The output row SET must equal the flat
    path's; the row ORDER may differ — that is the point."""
    from presto_tpu import types as T
    from presto_tpu.batch import Batch, Column
    from presto_tpu.exec.executor import Executor
    from presto_tpu.plan import nodes as P

    rng = np.random.default_rng(13)
    nl, nr = 1500, 2000
    lkeys = rng.integers(0, 500, nl).astype(np.int64)
    rkeys = rng.integers(0, 500, nr).astype(np.int64)
    left = Batch({"x": Column(jnp.asarray(lkeys), None, T.BIGINT, None)},
                 jnp.ones((nl,), bool))
    right = Batch({
        "y": Column(jnp.asarray(rkeys), None, T.BIGINT, None),
        "p": Column(jnp.asarray(rng.random(nr)), None, T.DOUBLE, None),
        "q": Column(jnp.asarray(rng.integers(0, 9, nr)),
                    jnp.asarray(rng.integers(0, 2, nr).astype(bool)),
                    T.BIGINT, None),
        "r": Column(jnp.asarray(rng.integers(0, 7, nr).astype(np.int32)),
                    None, T.INTEGER, None),
    }, jnp.ones((nr,), bool))
    node = P.Join(P.Values(), P.Values(), "INNER", [("x", "y")])

    def run(mark):
        ex = Executor.__new__(Executor)
        ex.static = False
        ex.guards = []
        ex.monitor = None
        ex.mem = None
        # ordering-aware execution state (a bare harness Executor skips
        # __init__; mirror its round-8 fields)
        ex.session = type("S", (), {"properties": {}})()
        ex.sort_stats = {}
        ex._sort_memo = {}
        ex._perm_memo = {}
        ex._batch_order = {}
        from presto_tpu.exec.executor import EvalContext

        ex.ctx = EvalContext()
        if mark:
            ex._oi_ids = {id(node)}
        out = ex._join_batches(left, right, node)
        sel = np.asarray(out.sel)
        rows = []
        for i in np.flatnonzero(sel):
            row = []
            for name in ("x", "y", "p", "q", "r"):
                c = out.columns[name]
                v = None if (c.valid is not None
                             and not bool(np.asarray(c.valid)[i])) \
                    else np.asarray(c.data)[i].item()
                row.append(v)
            rows.append(tuple(row))
        return sorted(rows, key=repr)

    assert G.sort_order_worthwhile(1, K.batch_word_width(right)
                                   - K.batch_word_width(left))
    marked = run(mark=True)
    flat = run(mark=False)
    assert marked == flat and len(marked) > 0
