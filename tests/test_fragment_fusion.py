"""Fragment fusion (ROADMAP open item 1): mesh-local exchange edges of a
cluster plan splice back into ONE traced shard_map program whose
Exchange nodes lower to ICI collectives (`plan/distribute.fuse_fragments`
+ `parallel/dist_executor.run_fused_fragment`), with the per-fragment
HTTP path as the byte-identical fallback for cross-host edges, kill
switches, and fault recovery."""

import json

import pytest

import presto_tpu
from presto_tpu.parallel import cluster as C
from tests.sqlite_oracle import assert_same_results, to_sqlite
from tests.tpch_queries import QUERIES


def norm(rows):
    return sorted(
        tuple(round(x, 4) if isinstance(x, float) else x for x in r)
        for r in rows)


def _counters(url):
    return json.loads(C._http(f"{url}/v1/info", timeout=10.0))["counters"]


# ---- fusion pass units ------------------------------------------------


def _fragments_for(session, sql, nw=1):
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.plan.distribute import distribute
    from presto_tpu.sql.parser import parse

    plan = plan_statement(session, parse(sql))
    dplan = distribute(plan, session, nw)
    return C.cut_fragments(dplan.root)


def test_fuse_fragments_full_splice(tpch_catalog_tiny):
    """Fusing every edge collapses the fragment DAG to ONE fragment
    whose root holds the original exchanges INLINE (no __exch_ scans),
    absorbing n-1 fragments."""
    from presto_tpu.plan import nodes as P
    from presto_tpu.plan.distribute import fuse_fragments

    s = presto_tpu.connect(tpch_catalog_tiny)
    frags = _fragments_for(
        s, "SELECT n_name, count(*) FROM customer, nation "
           "WHERE c_nationkey = n_nationkey GROUP BY n_name")
    assert len(frags) >= 2
    fused, n = fuse_fragments(frags, lambda f, i: True)
    assert n == len(frags) - 1
    assert len(fused) == 1 and getattr(fused[0], "fused", False)
    kinds, exch_scans = [], []

    def walk(node):
        if isinstance(node, P.Exchange):
            kinds.append(node.kind)
        if isinstance(node, P.TableScan) and node.table.startswith("__exch_"):
            exch_scans.append(node.table)
        for src in node.sources:
            walk(src)

    walk(fused[0].root)
    assert kinds and not exch_scans, (kinds, exch_scans)
    assert sorted(fused[0].fused_fids) == list(range(len(frags) - 1))


def test_fuse_fragments_partial_keeps_external_edge(tpch_catalog_tiny):
    """An excluded edge kind stays a cut: the super-fragment keeps an
    external __exch_ input (migrated producer inputs included) and the
    producer survives as its own fragment."""
    from presto_tpu.plan.distribute import fuse_fragments

    s = presto_tpu.connect(tpch_catalog_tiny)
    s.set("distributed_sort_threshold_rows", 100)
    frags = _fragments_for(
        s, "SELECT c_custkey, c_acctbal FROM customer "
           "ORDER BY c_acctbal DESC, c_custkey")
    assert any(i.kind == "range" for f in frags for i in f.inputs)
    fused, n = fuse_fragments(
        frags, lambda f, i: i.kind != "range")
    assert n >= 1 and len(fused) == len(frags) - n
    ext = [i for f in fused for i in f.inputs]
    assert [i.kind for i in ext] == ["range"]
    # producers renumbered consistently: every producer fid exists
    for f in fused:
        for i in f.inputs:
            assert 0 <= i.producer < f.fid


# ---- end-to-end over a declared-mesh worker ---------------------------


@pytest.fixture(scope="module")
def fusion_cluster(tpch_catalog_tiny):
    """In-process worker that DECLARES a 4-device mesh out of the
    8-device test process (the operator grant; workers never infer
    mesh ownership).  4 keeps the fused shard programs cheap on the
    1-core CI tier — the mechanism is ndev-independent."""
    session = presto_tpu.connect(tpch_catalog_tiny)
    w = C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache",
                       mesh_devices=4).start()
    cs = C.ClusterSession(session, [w.url])
    yield session, cs, w
    w.stop()


def test_worker_advertises_declared_mesh(fusion_cluster):
    _session, cs, w = fusion_cluster
    info = json.loads(C._http(f"{w.url}/v1/info", timeout=10.0))
    assert info["meshDevices"] == 4
    assert info["meshId"]
    # undeclared workers advertise no mesh (in-process default)
    w2 = C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache").start()
    try:
        assert json.loads(C._http(f"{w2.url}/v1/info",
                                  timeout=10.0))["meshDevices"] == 0
    finally:
        w2.stop()


@pytest.mark.parametrize("qid", [3,
                                 pytest.param(18, marks=pytest.mark.slow),
                                 pytest.param(21, marks=pytest.mark.slow)])
def test_fused_vs_cut_checksum_equivalence(qid, fusion_cluster,
                                           tpch_sqlite_tiny):
    """The acceptance gate: distributed q3(/q18/q21) executes as a
    single fused program on the mesh (fragments_fused > 0, zero
    exchange bytes through the host) with results identical to the
    fragment-cut path AND the sqlite oracle.  q18/q21's cut legs are
    tier-2 (the cut path's cold per-fragment execution costs tens of
    seconds on the 1-core CI tier); tier-1 covers q18 fused via
    test_q18_single_fused_program and the committed MULTICHIP_r07
    record carries the measured q18 fused-vs-cut-vs-auto equality."""
    session, cs, w = fusion_cluster
    session.set("fragment_fusion", True)
    fused = cs.sql(QUERIES[qid])
    st = fused.stats
    assert st.fragments_fused > 0, "did not fuse"
    assert st.exchange_bytes_host == 0, st.exchange_bytes_host
    assert st.exchange_bytes_collective > 0
    session.set("fragment_fusion", False)
    try:
        cut = cs.sql(QUERIES[qid])
    finally:
        session.set("fragment_fusion", True)
    assert cut.stats.fragments_fused == 0
    assert norm(fused.rows) == norm(cut.rows)
    expected = tpch_sqlite_tiny.execute(to_sqlite(QUERIES[qid])).fetchall()
    assert_same_results(fused.rows, expected, ordered=True)


def test_q18_single_fused_program(fusion_cluster, tpch_sqlite_tiny):
    """q18 (the deep join+agg gate query) fuses into ONE program with
    zero host exchange bytes and matches the sqlite oracle; its full
    fused-vs-cut leg is tier-2 + the committed MULTICHIP_r07 record;
    the round-18 AUTO leg (cost model picks cut here) lives in
    tests/test_fusion_cost.py."""
    session, cs, _w = fusion_cluster
    r = cs.sql(QUERIES[18])
    st = r.stats
    assert st.fragments_fused > 0
    assert st.exchange_bytes_host == 0
    assert st.exchange_bytes_collective > 0
    expected = tpch_sqlite_tiny.execute(to_sqlite(QUERIES[18])).fetchall()
    assert_same_results(r.rows, expected, ordered=True)


def test_fused_warm_run_reuses_executable(fusion_cluster):
    """One executable per fused pipeline (exec/compile_cache.fused_key):
    a warm re-run of a fused query compiles NOTHING on the worker."""
    session, cs, w = fusion_cluster
    cs.sql(QUERIES[3])  # ensure warm
    before = _counters(w.url)["compiles"]
    r = cs.sql(QUERIES[3])
    after = _counters(w.url)["compiles"]
    assert r.stats.fragments_fused > 0
    assert after == before, f"warm fused run recompiled ({after - before})"


def test_fused_worker_info_counters(fusion_cluster):
    """Satellite: worker /v1/info carries the fusion counters."""
    session, cs, w = fusion_cluster
    cs.sql(QUERIES[3])
    c = _counters(w.url)
    assert c["tasks_fused"] >= 1
    assert c["fragments_fused"] >= 1
    assert c["exchange_bytes_collective"] > 0


def test_partial_fusion_range_edge_stays_on_host(fusion_cluster,
                                                 tpch_sqlite_tiny):
    """fragment_fusion_kinds without `range`: the distributed sample
    sort's range edge stays an HTTP exchange between a scan fragment
    and the fused sort+output super-fragment — fragments still fuse,
    host exchange bytes are nonzero, order is exact."""
    session, cs, _w = fusion_cluster
    session.set("fragment_fusion_kinds",
                "repartition,broadcast,gather,scatter")
    session.set("distributed_sort_threshold_rows", 100)
    sql = ("SELECT c_custkey, c_acctbal FROM customer "
           "ORDER BY c_acctbal DESC, c_custkey")
    try:
        r = cs.sql(sql)
    finally:
        session.set("fragment_fusion_kinds", "")
        session.set("distributed_sort_threshold_rows", 100_000)
    st = r.stats
    assert st.fragments_fused > 0
    assert st.exchange_bytes_host > 0  # the unfused range edge
    expected = tpch_sqlite_tiny.execute(to_sqlite(sql)).fetchall()
    assert_same_results(r.rows, expected, ordered=True)


def test_cross_host_edges_do_not_fuse(fusion_cluster):
    """Forced cross-host topology: the worker's declared mesh falls
    below fragment_fusion_min_devices (a too-small mesh is no fusion
    target — same classifier verdict as an undeclared one), so every
    edge is cross-host: the per-fragment HTTP path runs, asserted via
    counters, with identical results."""
    session, cs, w = fusion_cluster
    fused_before = _counters(w.url)["tasks_fused"]
    session.set("fragment_fusion_min_devices", 99)
    q = ("SELECT n_name, count(*) c FROM customer, nation "
         "WHERE c_nationkey = n_nationkey GROUP BY n_name ORDER BY 1")
    try:
        r = cs.sql(q)
    finally:
        session.set("fragment_fusion_min_devices", 2)
    st = r.stats
    assert st.fragments_fused == 0
    assert st.exchange_bytes_host > 0  # pages crossed the host
    assert st.exchange_bytes_collective == 0
    assert norm(r.rows) == norm(session.sql(q).rows)
    assert _counters(w.url)["tasks_fused"] == fused_before


def test_fragment_fusion_kill_switches(fusion_cluster, monkeypatch):
    """Session property AND env kill switch each restore the old path
    exactly (fragments_fused == 0, host exchange bytes > 0, identical
    rows)."""
    session, cs, _w = fusion_cluster
    q = ("SELECT o_orderpriority, count(*) c FROM orders "
         "GROUP BY o_orderpriority ORDER BY 1")
    fused = cs.sql(q)
    assert fused.stats.fragments_fused > 0
    session.set("fragment_fusion", False)
    try:
        off = cs.sql(q)
    finally:
        session.set("fragment_fusion", True)
    assert off.stats.fragments_fused == 0
    assert off.stats.exchange_bytes_host > 0
    assert norm(off.rows) == norm(fused.rows)
    monkeypatch.setenv("PRESTO_TPU_FRAGMENT_FUSION", "off")
    env_off = cs.sql(q)
    assert env_off.stats.fragments_fused == 0
    assert norm(env_off.rows) == norm(fused.rows)
    monkeypatch.delenv("PRESTO_TPU_FRAGMENT_FUSION")


def test_fused_scalar_subquery_and_dynamic_filters(fusion_cluster):
    """Coordinator-evaluated scalar subqueries bake into the fused
    trace (and ride the executable-memo key); in-trace dynamic filters
    keep producing/applying inside the fused program."""
    session, cs, _w = fusion_cluster
    q = ("SELECT o_orderpriority, count(*) FROM orders "
         "WHERE o_totalprice > (SELECT avg(o_totalprice) FROM orders) "
         "GROUP BY o_orderpriority ORDER BY 1")
    r = cs.sql(q)
    assert r.stats.fragments_fused > 0
    assert norm(r.rows) == norm(session.sql(q).rows)


@pytest.mark.slow
def test_fused_all_22_tpch_queries_match_cut_path(fusion_cluster):
    """Tier-2 sweep: every TPC-H query agrees fused-vs-cut (shapes that
    cannot distribute fall back identically on both paths)."""
    session, cs, _w = fusion_cluster
    for qid in sorted(QUERIES):
        fused = cs.sql(QUERIES[qid])
        session.set("fragment_fusion", False)
        try:
            cut = cs.sql(QUERIES[qid])
        finally:
            session.set("fragment_fusion", True)
        assert norm(fused.rows) == norm(cut.rows), f"Q{qid}"
