"""P10 index joins: dense and strided-block (invertible sparse) build
keys lower the probe to one gather.

Reference: sql/planner/optimizations/IndexJoinOptimizer.java +
operator/index/IndexLoader; the TPU-native "index" is the closed-form
layout of the generator key — dense surrogates (customer, part) and
dbgen's sparse orderkey (8 keys per 32-key block, catalog.key_layout).
"""

import pytest

import presto_tpu
from presto_tpu.catalog import tpch_catalog

from tests.tpch_queries import QUERIES


@pytest.fixture(scope="module")
def s():
    return presto_tpu.connect(
        tpch_catalog(0.01, "/tmp/presto_tpu_cache"))


def test_q3_index_annotations(s):
    # both joins carry the INDEX annotation (customer dense, orders
    # strided); the executor takes the strided gather only when the
    # probe is not much wider than the build (Q3's 4x probe runs the
    # compacted sort join — measured faster on chip)
    txt = s.sql("EXPLAIN " + QUERIES[3]).rows[0][0]
    assert txt.count("INDEX") == 2


def test_strided_orderkey_join_exact(s):
    # join through the sparse orderkey: totals must match the
    # two-sided aggregation (oracle-free invariant)
    r = s.sql("SELECT count(*), sum(o_totalprice) FROM lineitem, orders "
              "WHERE l_orderkey = o_orderkey").rows
    n_li = s.sql("SELECT count(*) FROM lineitem").rows[0][0]
    assert r[0][0] == n_li  # every lineitem has its order
    per_order = s.sql(
        "SELECT sum(o_totalprice * cnt) FROM orders, "
        "(SELECT l_orderkey AS k, count(*) AS cnt FROM lineitem "
        "GROUP BY l_orderkey) g WHERE o_orderkey = g.k").rows[0][0]
    assert r[0][1] == pytest.approx(per_order, rel=1e-9)


def test_probing_missing_keys_between_blocks(s):
    # keys in the 24-key gap of each 32-key block must MISS, not
    # alias onto a neighbor row (the in_slot check)
    r = s.sql("SELECT count(*) FROM (VALUES (9), (10), (31), (33)) "
              "AS p(k) LEFT JOIN orders ON k = o_orderkey "
              "WHERE o_orderkey IS NOT NULL").rows
    # dbgen block 0 holds keys 1..8; 9/10/31 are gaps, 33 exists
    assert r == [(1,)]


def test_left_join_null_extension_through_index(s):
    rows = s.sql("SELECT k, o_orderkey FROM (VALUES (1), (9)) AS p(k) "
                 "LEFT JOIN orders ON k = o_orderkey ORDER BY k").rows
    assert rows == [(1, 1), (9, None)]
