"""Differential correctness: all 22 TPC-H queries vs the sqlite oracle on
identical generated data (reference analog: AbstractTestQueries vs
H2QueryRunner, presto-tests)."""

import pytest

import presto_tpu
from tests.sqlite_oracle import assert_same_results, to_sqlite
from tests.tpch_queries import QUERIES

ORDERED = {1, 2, 3, 4, 5, 7, 8, 9, 10, 11, 12, 13, 15, 16, 18, 20, 21, 22}


@pytest.fixture(scope="module")
def session(tpch_catalog_tiny):
    return presto_tpu.connect(tpch_catalog_tiny)


# q21 is the suite's single heaviest dynamic-mode compile (~40s on the
# 1-core CI box); its correctness stays covered every run by
# test_distributed.test_all_22_tpch_queries_distribute (collective
# path) and the tier-2 run keeps this oracle leg (round-12 budget fit,
# same rule as the round-6 demotions)
@pytest.mark.parametrize("qid", [
    pytest.param(q, marks=pytest.mark.slow) if q == 21 else q
    for q in sorted(QUERIES)])
def test_tpch_query(qid, session, tpch_sqlite_tiny):
    sql = QUERIES[qid]
    actual = session.sql(sql)
    expected = tpch_sqlite_tiny.execute(to_sqlite(sql)).fetchall()
    assert_same_results(actual.rows, expected, ordered=qid in ORDERED)
