"""Differential correctness: all 22 TPC-H queries vs the sqlite oracle on
identical generated data (reference analog: AbstractTestQueries vs
H2QueryRunner, presto-tests)."""

import pytest

import presto_tpu
from tests.sqlite_oracle import assert_same_results, to_sqlite
from tests.tpch_queries import QUERIES

ORDERED = {1, 2, 3, 4, 5, 7, 8, 9, 10, 11, 12, 13, 15, 16, 18, 20, 21, 22}


@pytest.fixture(scope="module")
def session(tpch_catalog_tiny):
    return presto_tpu.connect(tpch_catalog_tiny)


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpch_query(qid, session, tpch_sqlite_tiny):
    sql = QUERIES[qid]
    actual = session.sql(sql)
    expected = tpch_sqlite_tiny.execute(to_sqlite(sql)).fetchall()
    assert_same_results(actual.rows, expected, ordered=qid in ORDERED)
