"""ORC read path (storage/orc.py + connectors/orc.py) validated against
an INDEPENDENT implementation: pyarrow.orc writes every file our
decoder reads — all codecs, RLEv2 sub-encodings, dictionary strings,
present streams, multiple stripes.

Reference parity target: presto-orc/ readers via the hive connector's
OrcPageSourceFactory."""

import numpy as np
import pyarrow as pa
import pyarrow.orc as po
import pytest

import presto_tpu
from presto_tpu.catalog import Catalog
from presto_tpu.connectors.orc import OrcTable
from presto_tpu.storage.orc import OrcFile, _IntRle


@pytest.fixture()
def rich_table():
    rng = np.random.default_rng(7)
    n = 6000
    return pa.table({
        "i32": pa.array(rng.integers(-1000, 1000, n), pa.int32()),
        "i64": pa.array(rng.integers(-10**12, 10**12, n), pa.int64()),
        "f32": pa.array(rng.normal(size=n).astype(np.float32)),
        "f64": pa.array(rng.normal(size=n)),
        "s": pa.array([f"val{int(x)}" for x in rng.integers(0, 60, n)]),
        "b": pa.array(rng.integers(0, 2, n).astype(bool)),
        "opt": pa.array([None if x % 5 == 0 else int(x)
                         for x in range(n)], pa.int64()),
        "d": pa.array(rng.integers(0, 20000, n).astype(np.int32),
                      pa.date32()),
        "mono": pa.array(np.cumsum(rng.integers(0, 3, n)), pa.int64()),
    })


def _assert_matches(path, table):
    ours = OrcFile(path)
    want = table.to_pydict()
    assert ours.num_rows == table.num_rows
    by_name = {c.name: c for c in ours.columns}
    for name in table.column_names:
        col = by_name[name]
        got, ok = [], []
        for si in range(len(ours.stripes)):
            vals, valid, _t = ours.read_column(si, col)
            got.extend(vals.tolist())
            ok.extend(valid.tolist() if valid is not None
                      else [True] * len(vals))
        for g, o, e in zip(got, ok, want[name]):
            if e is None:
                assert not o, (name, g)
                continue
            assert o, (name, e)
            if hasattr(e, "toordinal"):
                e = e.toordinal() - 719163
            if isinstance(e, float):
                assert g == pytest.approx(e, rel=1e-6)
            else:
                assert g == e, (name, g, e)


@pytest.mark.parametrize("codec", ["uncompressed", "zlib", "snappy",
                                   "zstd", "lz4"])
def test_read_pyarrow_orc_all_codecs(tmp_path, rich_table, codec):
    if codec == "zstd":
        pytest.importorskip("zstandard")  # optional codec dep -> skip
    p = str(tmp_path / f"t_{codec}.orc")
    po.write_table(rich_table, p, compression=codec)
    _assert_matches(p, rich_table)


def test_multiple_stripes(tmp_path, rich_table):
    p = str(tmp_path / "stripes.orc")
    po.write_table(rich_table, p, stripe_size=16384, batch_size=1000)
    f = OrcFile(p)
    assert len(f.stripes) > 1  # the per-stripe path is really exercised
    _assert_matches(p, rich_table)


def test_rlev2_subencodings_roundtrip(tmp_path):
    """Data shaped to force each RLE v2 sub-encoding: constant runs
    (short repeat), random (direct), monotonic (delta), and skewed
    outliers (patched base)."""
    n = 2000
    rng = np.random.default_rng(3)
    base = rng.integers(0, 100, n)
    base[::97] = 10**9  # outliers -> patched base candidates
    tbl = pa.table({
        "const": pa.array(np.full(n, 42), pa.int64()),
        "rand": pa.array(rng.integers(-10**9, 10**9, n), pa.int64()),
        "mono": pa.array(np.arange(n) * 3 + 7, pa.int64()),
        "skew": pa.array(base, pa.int64()),
    })
    p = str(tmp_path / "rle2.orc")
    po.write_table(tbl, p, compression="uncompressed")
    _assert_matches(p, tbl)


def test_orc_connector_sql(tmp_path, rich_table):
    pytest.importorskip("zstandard")  # file written with zstd below
    p = str(tmp_path / "t.orc")
    po.write_table(rich_table, p, compression="zstd")
    cat = Catalog()
    cat.register(OrcTable("orc_t", p))
    s = presto_tpu.connect(cat)
    want = rich_table.to_pydict()
    assert s.sql("SELECT count(*) FROM orc_t").rows[0][0] \
        == rich_table.num_rows
    total = s.sql("SELECT sum(i64), count(opt) FROM orc_t").rows[0]
    assert total[0] == sum(want["i64"])
    assert total[1] == sum(1 for v in want["opt"] if v is not None)
    top = s.sql("SELECT s, count(*) c FROM orc_t GROUP BY s "
                "ORDER BY c DESC, s LIMIT 3").rows
    import collections

    cnt = collections.Counter(want["s"])
    expect = sorted(cnt.items(), key=lambda kv: (-kv[1], kv[0]))[:3]
    assert [(r[0], r[1]) for r in top] == expect


def test_orc_splits_align_to_stripes(tmp_path, rich_table):
    p = str(tmp_path / "t.orc")
    po.write_table(rich_table, p, stripe_size=16384, batch_size=1000)
    t = OrcTable("t", p)
    splits = t.splits(4)
    assert sum(b - a for a, b in splits) == rich_table.num_rows
    got = np.concatenate([t.read(["i64"], sp)["i64"] for sp in splits])
    assert got.tolist() == rich_table.to_pydict()["i64"]


def test_int_rle_v1():
    # v1 run: header=2 (5 values), delta=1, base=100 (varint 100)
    data = bytes([2, 1, 100])
    vals = _IntRle(data, signed=False, v2=False).read(5)
    assert vals.tolist() == [100, 101, 102, 103, 104]
    # v1 literals: header=0xFE (2 literals), zigzag varints 1, -1
    data = bytes([0xFE, 2, 1])
    vals = _IntRle(data, signed=True, v2=False).read(2)
    assert vals.tolist() == [1, -1]


def test_orc_writer_read_by_pyarrow(tmp_path):
    """Our ORC writer's files parse in an independent implementation."""
    from presto_tpu import types as T
    from presto_tpu.storage.orc import write_orc

    p = str(tmp_path / "w.orc")
    arrays = {
        "a": np.arange(200, dtype=np.int64) * 3 - 50,
        "s": np.asarray([f"name{i % 7}" for i in range(200)],
                        dtype=object),
        "f": np.ma.masked_array(np.arange(200) * 0.25,
                                np.arange(200) % 6 == 0),
        "flag": np.arange(200) % 2 == 0,
        "d": np.arange(200, dtype=np.int32) + 19000,
    }
    schema = {"a": T.BIGINT, "s": T.VARCHAR, "f": T.DOUBLE,
              "flag": T.BOOLEAN, "d": T.DATE}
    write_orc(p, arrays, schema)
    t = po.read_table(p)
    assert t.column("a").to_pylist() == (np.arange(200) * 3 - 50).tolist()
    got_f = t.column("f").to_pylist()
    assert all((v is None) == (i % 6 == 0) for i, v in enumerate(got_f))
    assert t.column("flag").to_pylist() == [i % 2 == 0
                                            for i in range(200)]
    # and our own reader round-trips it
    _assert_matches(p, t)


def test_orc_ctas_and_insert(tmp_path):
    import presto_tpu as _pt
    from presto_tpu.catalog import Catalog as _Cat

    s = _pt.connect(_Cat())
    s.set("localfile_root", str(tmp_path))
    s.sql("CREATE TABLE ot WITH (connector = 'orc') AS "
          "SELECT a, a * 3 AS b FROM (VALUES (1), (2), (3)) t(a)")
    assert s.sql("SELECT sum(b) FROM ot").rows == [(18,)]
    s.sql("INSERT INTO ot SELECT a, a * 3 FROM (VALUES (10)) t(a)")
    assert s.sql("SELECT count(*), sum(b) FROM ot").rows == [(4, 48)]
    # first committed part file (staged-sink naming carries the
    # manifest generation) still reads back with an independent reader
    parts = sorted(p for p in (tmp_path / "ot").iterdir()
                   if p.name.endswith(".orc"))
    back = po.read_table(str(parts[0]))
    assert sorted(back.column("a").to_pylist()) == [1, 2, 3]


def test_orc_timestamp_roundtrip_both_ways(tmp_path):
    """Review regression: timestamp SECONDARY streams are kind 5."""
    from presto_tpu import types as T
    from presto_tpu.storage.orc import write_orc

    micros = np.asarray([0, 1_500_000, 1_700_000_123_456_789 // 1000],
                        np.int64)
    p = str(tmp_path / "ts.orc")
    write_orc(p, {"t": micros}, {"t": T.TIMESTAMP})
    got = po.read_table(p).column("t").to_pylist()
    assert [int(v.timestamp() * 1e6) for v in got] == micros.tolist()
    f = OrcFile(p)
    vals, valid, _ = f.read_column(0, f.columns[0])
    assert vals.tolist() == micros.tolist()
    # and a pyarrow-written timestamp file reads back
    p2 = str(tmp_path / "ts2.orc")
    tb = pa.table({"t": pa.array(micros, pa.timestamp("us"))})
    po.write_table(tb, p2)
    f2 = OrcFile(p2)
    vals2, _v, _t = f2.read_column(0, f2.columns[0])
    assert vals2.tolist() == micros.tolist()


def test_orc_ctas_rejects_stale_directory(tmp_path):
    import presto_tpu as _pt
    from presto_tpu.catalog import Catalog as _Cat

    s = _pt.connect(_Cat())
    s.set("localfile_root", str(tmp_path))
    s.sql("CREATE TABLE st WITH (connector='orc') AS "
          "SELECT 1 AS a FROM (VALUES (0)) v(z)")
    s2 = _pt.connect(_Cat())
    s2.set("localfile_root", str(tmp_path))
    with pytest.raises(Exception):
        s2.sql("CREATE TABLE st WITH (connector='orc') AS "
               "SELECT 2 AS a FROM (VALUES (0)) v(z)")


def test_orc_insert_nulls(tmp_path):
    import presto_tpu as _pt
    from presto_tpu.catalog import Catalog as _Cat

    s = _pt.connect(_Cat())
    s.set("localfile_root", str(tmp_path))
    s.sql("CREATE TABLE nt WITH (connector='orc') AS "
          "SELECT a FROM (VALUES (1), (CAST(NULL AS BIGINT))) t(a)")
    assert s.sql("SELECT count(*), count(a) FROM nt").rows == [(2, 1)]
    s.sql("INSERT INTO nt SELECT CAST(NULL AS BIGINT) "
          "FROM (VALUES (0)) v(z)")
    assert s.sql("SELECT count(*), count(a) FROM nt").rows == [(3, 1)]
