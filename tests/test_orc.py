"""ORC read path (storage/orc.py + connectors/orc.py) validated against
an INDEPENDENT implementation: pyarrow.orc writes every file our
decoder reads — all codecs, RLEv2 sub-encodings, dictionary strings,
present streams, multiple stripes.

Reference parity target: presto-orc/ readers via the hive connector's
OrcPageSourceFactory."""

import numpy as np
import pyarrow as pa
import pyarrow.orc as po
import pytest

import presto_tpu
from presto_tpu.catalog import Catalog
from presto_tpu.connectors.orc import OrcTable
from presto_tpu.storage.orc import OrcFile, _IntRle


@pytest.fixture()
def rich_table():
    rng = np.random.default_rng(7)
    n = 6000
    return pa.table({
        "i32": pa.array(rng.integers(-1000, 1000, n), pa.int32()),
        "i64": pa.array(rng.integers(-10**12, 10**12, n), pa.int64()),
        "f32": pa.array(rng.normal(size=n).astype(np.float32)),
        "f64": pa.array(rng.normal(size=n)),
        "s": pa.array([f"val{int(x)}" for x in rng.integers(0, 60, n)]),
        "b": pa.array(rng.integers(0, 2, n).astype(bool)),
        "opt": pa.array([None if x % 5 == 0 else int(x)
                         for x in range(n)], pa.int64()),
        "d": pa.array(rng.integers(0, 20000, n).astype(np.int32),
                      pa.date32()),
        "mono": pa.array(np.cumsum(rng.integers(0, 3, n)), pa.int64()),
    })


def _assert_matches(path, table):
    ours = OrcFile(path)
    want = table.to_pydict()
    assert ours.num_rows == table.num_rows
    by_name = {c.name: c for c in ours.columns}
    for name in table.column_names:
        col = by_name[name]
        got, ok = [], []
        for si in range(len(ours.stripes)):
            vals, valid, _t = ours.read_column(si, col)
            got.extend(vals.tolist())
            ok.extend(valid.tolist() if valid is not None
                      else [True] * len(vals))
        for g, o, e in zip(got, ok, want[name]):
            if e is None:
                assert not o, (name, g)
                continue
            assert o, (name, e)
            if hasattr(e, "toordinal"):
                e = e.toordinal() - 719163
            if isinstance(e, float):
                assert g == pytest.approx(e, rel=1e-6)
            else:
                assert g == e, (name, g, e)


@pytest.mark.parametrize("codec", ["uncompressed", "zlib", "snappy",
                                   "zstd", "lz4"])
def test_read_pyarrow_orc_all_codecs(tmp_path, rich_table, codec):
    p = str(tmp_path / f"t_{codec}.orc")
    po.write_table(rich_table, p, compression=codec)
    _assert_matches(p, rich_table)


def test_multiple_stripes(tmp_path, rich_table):
    p = str(tmp_path / "stripes.orc")
    po.write_table(rich_table, p, stripe_size=16384, batch_size=1000)
    f = OrcFile(p)
    assert len(f.stripes) > 1  # the per-stripe path is really exercised
    _assert_matches(p, rich_table)


def test_rlev2_subencodings_roundtrip(tmp_path):
    """Data shaped to force each RLE v2 sub-encoding: constant runs
    (short repeat), random (direct), monotonic (delta), and skewed
    outliers (patched base)."""
    n = 2000
    rng = np.random.default_rng(3)
    base = rng.integers(0, 100, n)
    base[::97] = 10**9  # outliers -> patched base candidates
    tbl = pa.table({
        "const": pa.array(np.full(n, 42), pa.int64()),
        "rand": pa.array(rng.integers(-10**9, 10**9, n), pa.int64()),
        "mono": pa.array(np.arange(n) * 3 + 7, pa.int64()),
        "skew": pa.array(base, pa.int64()),
    })
    p = str(tmp_path / "rle2.orc")
    po.write_table(tbl, p, compression="uncompressed")
    _assert_matches(p, tbl)


def test_orc_connector_sql(tmp_path, rich_table):
    p = str(tmp_path / "t.orc")
    po.write_table(rich_table, p, compression="zstd")
    cat = Catalog()
    cat.register(OrcTable("orc_t", p))
    s = presto_tpu.connect(cat)
    want = rich_table.to_pydict()
    assert s.sql("SELECT count(*) FROM orc_t").rows[0][0] \
        == rich_table.num_rows
    total = s.sql("SELECT sum(i64), count(opt) FROM orc_t").rows[0]
    assert total[0] == sum(want["i64"])
    assert total[1] == sum(1 for v in want["opt"] if v is not None)
    top = s.sql("SELECT s, count(*) c FROM orc_t GROUP BY s "
                "ORDER BY c DESC, s LIMIT 3").rows
    import collections

    cnt = collections.Counter(want["s"])
    expect = sorted(cnt.items(), key=lambda kv: (-kv[1], kv[0]))[:3]
    assert [(r[0], r[1]) for r in top] == expect


def test_orc_splits_align_to_stripes(tmp_path, rich_table):
    p = str(tmp_path / "t.orc")
    po.write_table(rich_table, p, stripe_size=16384, batch_size=1000)
    t = OrcTable("t", p)
    splits = t.splits(4)
    assert sum(b - a for a, b in splits) == rich_table.num_rows
    got = np.concatenate([t.read(["i64"], sp)["i64"] for sp in splits])
    assert got.tolist() == rich_table.to_pydict()["i64"]


def test_int_rle_v1():
    # v1 run: header=2 (5 values), delta=1, base=100 (varint 100)
    data = bytes([2, 1, 100])
    vals = _IntRle(data, signed=False, v2=False).read(5)
    assert vals.tolist() == [100, 101, 102, 103, 104]
    # v1 literals: header=0xFE (2 literals), zigzag varints 1, -1
    data = bytes([0xFE, 2, 1])
    vals = _IntRle(data, signed=True, v2=False).read(2)
    assert vals.tolist() == [1, -1]
