"""Differential-testing oracle: load generated data into sqlite and run the
same SQL there (reference analog: H2QueryRunner + QueryAssertions,
presto-tests/src/main/java/com/facebook/presto/tests/)."""

from __future__ import annotations

import math
import sqlite3
from typing import Iterable

import numpy as np

from presto_tpu.connectors import tpch as tpch_gen

_CONNS: dict = {}


def build_sqlite(sf: float = 0.01) -> sqlite3.Connection:
    if sf in _CONNS:
        return _CONNS[sf]
    conn = sqlite3.connect(":memory:")
    for table, schema in tpch_gen.SCHEMAS.items():
        data = tpch_gen.generate(table, sf)
        cols = list(schema)
        decls = []
        for c in cols:
            t = schema[c]
            if t.is_integer:
                decls.append(f"{c} INTEGER")
            elif t.name == "DATE":
                decls.append(f"{c} INTEGER")  # days since epoch, matches engine repr
            elif t.is_numeric:
                decls.append(f"{c} REAL")
            else:
                decls.append(f"{c} TEXT")
        conn.execute(f"CREATE TABLE {table} ({', '.join(decls)})")
        arrays = []
        for c in cols:
            a = data[c]
            if a.dtype == object:
                arrays.append(a.tolist())
            elif a.dtype.kind in "iu":
                arrays.append([int(x) for x in a])
            else:
                arrays.append([float(x) for x in a])
        rows = list(zip(*arrays))
        conn.executemany(
            f"INSERT INTO {table} VALUES ({','.join('?' * len(cols))})", rows
        )
    conn.commit()
    _CONNS[sf] = conn
    return conn


def normalize(rows: Iterable[tuple]) -> list:
    out = []
    for row in rows:
        norm = []
        for v in row:
            if isinstance(v, (np.generic,)):
                v = v.item()
            if isinstance(v, float):
                norm.append(round(v, 4))
            elif isinstance(v, np.ma.core.MaskedConstant):
                norm.append(None)
            else:
                norm.append(v)
        out.append(tuple(norm))
    return out


def assert_same_results(actual_rows, expected_rows, ordered: bool = False, rel_tol=1e-6):
    a = normalize(actual_rows)
    e = normalize(expected_rows)
    if not ordered:
        a = sorted(a, key=repr)
        e = sorted(e, key=repr)
    assert len(a) == len(e), f"row count {len(a)} != {len(e)}\nactual[:5]={a[:5]}\nexpected[:5]={e[:5]}"
    for i, (ra, re_) in enumerate(zip(a, e)):
        assert len(ra) == len(re_), f"row {i}: width {len(ra)} != {len(re_)}"
        for j, (va, ve) in enumerate(zip(ra, re_)):
            if isinstance(va, float) or isinstance(ve, float):
                if va is None or ve is None:
                    assert va is None and ve is None, f"row {i} col {j}: {va} != {ve}"
                    continue
                assert math.isclose(float(va), float(ve), rel_tol=rel_tol, abs_tol=1e-4), (
                    f"row {i} col {j}: {va} != {ve}\nactual={ra}\nexpected={re_}"
                )
            else:
                assert va == ve, f"row {i} col {j}: {va!r} != {ve!r}\nactual={ra}\nexpected={re_}"
