"""Differential-testing oracle: load generated data into sqlite and run the
same SQL there (reference analog: H2QueryRunner + QueryAssertions,
presto-tests/src/main/java/com/facebook/presto/tests/)."""

from __future__ import annotations

import math
import sqlite3
from typing import Iterable

import numpy as np

from presto_tpu.connectors import tpch as tpch_gen

_CONNS: dict = {}


def build_sqlite(sf: float = 0.01, generator=None) -> sqlite3.Connection:
    """Load a generator module's tables (default: TPC-H; pass
    presto_tpu.connectors.tpcds for TPC-DS) into an in-memory sqlite."""
    gen = generator or tpch_gen
    key = (gen.__name__, sf)
    if key in _CONNS:
        return _CONNS[key]
    conn = sqlite3.connect(":memory:")

    class _Stddev:
        """Welford sample stddev (sqlite has no stddev built in)."""

        def __init__(self):
            self.n, self.mean, self.m2 = 0, 0.0, 0.0

        def step(self, v):
            if v is None:
                return
            self.n += 1
            d = v - self.mean
            self.mean += d / self.n
            self.m2 += d * (v - self.mean)

        def finalize(self):
            if self.n < 2:
                return None
            return math.sqrt(self.m2 / (self.n - 1))

    conn.create_aggregate("stddev_samp", 1, _Stddev)
    conn.create_aggregate("stddev", 1, _Stddev)
    for table, schema in gen.SCHEMAS.items():
        data = gen.generate(table, sf)
        cols = list(schema)
        decls = []
        for c in cols:
            t = schema[c]
            if t.is_integer:
                decls.append(f"{c} INTEGER")
            elif t.name == "DATE":
                decls.append(f"{c} INTEGER")  # days since epoch, matches engine repr
            elif t.is_numeric:
                decls.append(f"{c} REAL")
            else:
                decls.append(f"{c} TEXT")
        conn.execute(f"CREATE TABLE {table} ({', '.join(decls)})")
        arrays = []
        for c in cols:
            a = data[c]
            if a.dtype == object:
                arrays.append(a.tolist())
            elif a.dtype.kind in "iu":
                arrays.append([int(x) for x in a])
            else:
                arrays.append([float(x) for x in a])
        rows = list(zip(*arrays))
        conn.executemany(
            f"INSERT INTO {table} VALUES ({','.join('?' * len(cols))})", rows
        )
    conn.commit()
    _CONNS[key] = conn
    return conn


def normalize(rows: Iterable[tuple]) -> list:
    out = []
    for row in rows:
        norm = []
        for v in row:
            if isinstance(v, (np.generic,)):
                v = v.item()
            if isinstance(v, float):
                norm.append(round(v, 4))
            elif isinstance(v, np.ma.core.MaskedConstant):
                norm.append(None)
            else:
                norm.append(v)
        out.append(tuple(norm))
    return out


def assert_same_results(actual_rows, expected_rows, ordered: bool = False,
                        rel_tol=1e-6, abs_tol=1e-4):
    a = normalize(actual_rows)
    e = normalize(expected_rows)
    if not ordered:
        a = sorted(a, key=repr)
        e = sorted(e, key=repr)
    assert len(a) == len(e), f"row count {len(a)} != {len(e)}\nactual[:5]={a[:5]}\nexpected[:5]={e[:5]}"
    for i, (ra, re_) in enumerate(zip(a, e)):
        assert len(ra) == len(re_), f"row {i}: width {len(ra)} != {len(re_)}"
        for j, (va, ve) in enumerate(zip(ra, re_)):
            if isinstance(va, float) or isinstance(ve, float):
                if va is None or ve is None:
                    assert va is None and ve is None, f"row {i} col {j}: {va} != {ve}"
                    continue
                assert math.isclose(float(va), float(ve), rel_tol=rel_tol,
                                    abs_tol=abs_tol), (
                    f"row {i} col {j}: {va} != {ve}\nactual={ra}\nexpected={re_}"
                )
            else:
                assert va == ve, f"row {i} col {j}: {va!r} != {ve!r}\nactual={ra}\nexpected={re_}"


# ---------------------------------------------------------------------------
# dialect translation: engine SQL -> sqlite SQL over the int-days date repr
# ---------------------------------------------------------------------------

import re as _re


def _date_days(s: str) -> int:
    return int((np.datetime64(s, "D") - np.datetime64("1970-01-01", "D"))
               / np.timedelta64(1, "D"))


def _shift(date_str: str, sign: int, n: int, unit: str) -> int:
    d = np.datetime64(date_str, "D")
    if unit in ("DAY", "WEEK"):
        delta = n * (7 if unit == "WEEK" else 1)
        return _date_days(str(d)) + sign * delta
    months = n * (12 if unit == "YEAR" else 1)
    m = np.datetime64(date_str[:7], "M") + sign * months
    day = int(date_str[8:10])
    # clamp to month end
    next_m = m + 1
    last = int((next_m.astype("datetime64[D]") - np.timedelta64(1, "D"))
               .astype(object).day)
    day = min(day, last)
    return _date_days(f"{str(m)}-{day:02d}")


def to_sqlite(sql: str) -> str:
    """Translate engine SQL to sqlite SQL (dates are integer days there)."""
    # DATE 'x' +/- INTERVAL 'n' UNIT  -> folded integer
    pat = _re.compile(
        r"DATE\s+'(\d{4}-\d{2}-\d{2})'\s*([+-])\s*INTERVAL\s+'(\d+)'\s+(DAY|WEEK|MONTH|YEAR)",
        _re.IGNORECASE)
    while True:
        m = pat.search(sql)
        if not m:
            break
        days = _shift(m.group(1), 1 if m.group(2) == "+" else -1,
                      int(m.group(3)), m.group(4).upper())
        sql = sql[:m.start()] + str(days) + sql[m.end():]
    # bare DATE literals
    sql = _re.sub(r"DATE\s+'(\d{4}-\d{2}-\d{2})'",
                  lambda m: str(_date_days(m.group(1))), sql)
    # EXTRACT(YEAR FROM e)
    sql = _re.sub(
        r"EXTRACT\s*\(\s*YEAR\s+FROM\s+([A-Za-z_][\w.]*)\s*\)",
        r"CAST(strftime('%Y', (\1)*86400, 'unixepoch') AS INTEGER)", sql,
        flags=_re.IGNORECASE)
    sql = _re.sub(
        r"EXTRACT\s*\(\s*MONTH\s+FROM\s+([A-Za-z_][\w.]*)\s*\)",
        r"CAST(strftime('%m', (\1)*86400, 'unixepoch') AS INTEGER)", sql,
        flags=_re.IGNORECASE)
    sql = _re.sub(r"\bsubstring\s*\(", "substr(", sql, flags=_re.IGNORECASE)
    return sql
