"""Chaos smoke: deterministic fault-injected recovery paths (ISSUE 2).

Every scenario runs against an IN-PROCESS cluster (no subprocess kills)
with a scripted FaultPlan, so each recovery path — backoff absorbing a
transient 500, circuit-breaking a crashed worker, straggler hedging,
deadline cancellation — is a fast, reproducible unit test asserted via
QueryStats.recovery counters.  The harness is seeded: a fixed seed
reproduces the exact firing pattern and backoff delays."""

import pytest

import presto_tpu
from presto_tpu.observe.events import EventListener
from presto_tpu.parallel import cluster as C
from presto_tpu.parallel import faults as F
from presto_tpu.parallel import retry as R


def norm(rows):
    return [tuple(round(x, 4) if isinstance(x, float) else x for x in r)
            for r in rows]


# ---- deterministic primitives -----------------------------------------


def test_fault_plan_grammar_compact_and_json():
    p = F.FaultPlan.parse(
        "server:GET:/results/:2:http500;exec:EXEC:*:1:delay:2.5;"
        "client:*:/v1/task:3+:reset")
    assert [r.action for r in p.rules] == ["http500", "delay", "reset"]
    assert p.rules[1].arg == 2.5
    assert p.rules[2].count == 0  # '3+' = every match from the 3rd on
    pj = F.FaultPlan.parse(
        '[{"where":"server","path":"/results/","nth":2,"action":"drop"}]')
    assert pj.rules[0].where == "server" and pj.rules[0].nth == 2
    with pytest.raises(ValueError):
        F.FaultPlan.parse("server:GET:/x:1:frobnicate")


def test_fault_plan_nth_matching_is_deterministic():
    p = F.FaultPlan.parse("server:GET:/results/:2:http500")
    assert p.match("server", "GET", "/v1/task/t/results/0/0") is None
    assert p.match("server", "GET", "/v1/task/t/results/0/0") is not None
    assert p.match("server", "GET", "/v1/task/t/results/0/0") is None
    assert p.match("server", "GET", "/v1/status") is None  # path filter
    assert len(p.fired) == 1


def test_fault_plan_probability_seeded():
    mk = lambda seed: F.FaultPlan(  # noqa: E731
        [F.FaultRule(where="client", nth=1, count=0, p=0.5)], seed=seed)
    fires = lambda plan: [  # noqa: E731
        plan.match("client", "GET", "/x") is not None for _ in range(32)]
    a, b = mk(7), mk(7)
    assert fires(a) == fires(b)  # same seed -> identical firing pattern
    assert any(fires(mk(8))) and 0 < sum(fires(mk(9))) < 32


def test_retry_policy_decorrelated_jitter_deterministic():
    a = R.RetryPolicy(seed=3)
    b = R.RetryPolicy(seed=3)
    da = [a.next_delay(d) for d in (0.02, 0.1, 0.5, 2.0)]
    db = [b.next_delay(d) for d in (0.02, 0.1, 0.5, 2.0)]
    assert da == db
    assert all(x <= a.cap_s for x in da)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("boom")
        return "ok"

    assert R.RetryPolicy(seed=1, base_s=0.001, cap_s=0.002).call(
        flaky, retryable=lambda e: True) == "ok"
    assert len(calls) == 3
    with pytest.raises(ValueError):
        R.RetryPolicy(seed=1).call(
            lambda: (_ for _ in ()).throw(ValueError("x")),
            retryable=lambda e: isinstance(e, ConnectionError))


def test_deadline_caps_and_expires():
    d = R.Deadline(60.0)
    assert 0 < d.cap(5.0) <= 5.0
    assert not d.expired()
    e = R.Deadline(-1.0)
    assert e.expired()
    with pytest.raises(R.DeadlineExceeded):
        e.cap(5.0)
    with pytest.raises(TimeoutError):  # DeadlineExceeded IS a timeout
        e.check("x")
    assert R.Deadline.never().cap(7.0) == 7.0


def test_health_board_trip_and_probation():
    clock = [0.0]
    hb = R.HealthBoard(trip_after=3, probation_s=5.0,
                       clock=lambda: clock[0])
    u = "http://w1"
    assert hb.record_fail(u) is False
    assert hb.record_fail(u) is False
    assert hb.record_fail(u) is True  # third consecutive failure trips
    assert hb.state(u) == "open" and not hb.allow(u)
    clock[0] = 6.0  # probation elapsed: one probe re-admitted
    assert hb.allow(u) and hb.state(u) == "probation"
    assert hb.record_fail(u) is True  # probation failure re-opens
    assert not hb.allow(u)
    clock[0] = 12.0
    assert hb.allow(u)
    hb.record_ok(u)
    assert hb.state(u) == "closed" and hb.allow(u)


# ---- fault-injected in-process cluster (the chaos smoke) --------------


QUERY = ("SELECT o_orderpriority, count(*) c FROM orders "
         "GROUP BY o_orderpriority ORDER BY 1")


@pytest.fixture(scope="module")
def chaos(tpch_catalog_tiny):
    session = presto_tpu.connect(tpch_catalog_tiny)
    workers = [C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache",
                              faults=F.FaultPlan([])).start()
               for _ in range(2)]
    cs = C.ClusterSession(session, [w.url for w in workers])
    want = norm(session.sql(QUERY).rows)
    assert norm(cs.sql(QUERY).rows) == want  # prewarm (compile + caches)
    yield session, cs, workers, want
    F.install(None)
    for w in workers:
        if not w.crashed:
            w.stop()


def _reset(session, cs, workers):
    for w in workers:
        w.faults = F.FaultPlan([])
    F.install(None)
    session.properties["cluster_query_deadline_s"] = None


def test_transient_500_absorbed_by_backoff(chaos):
    """Acceptance: a scripted one-shot 500 on the results endpoint is
    absorbed by retry/backoff — ZERO query-level retries."""
    session, cs, workers, want = chaos
    seen = []

    class Tap(EventListener):
        def recovery(self, event):
            seen.append(event.kind)

    session.event_listeners.append(Tap())
    try:
        workers[0].faults = F.FaultPlan.parse("server:GET:/results/:1:http500")
        assert norm(cs.sql(QUERY).rows) == want
        rec = session.last_stats.recovery
        assert rec.get("http_retries", 0) >= 1, rec
        assert "query_retries" not in rec, rec
        assert len(workers[0].faults.fired) == 1  # fired exactly once
        assert "http_retries" in seen  # RecoveryEvent reached listeners
    finally:
        session.event_listeners.pop()
        _reset(session, cs, workers)


def test_partial_page_reverified_and_repulled(chaos):
    """A corrupted (truncated) page transfer fails the PTPG checksum on
    receipt and is re-requested by sequence token — at-least-once
    delivery, not a poisoned consumer."""
    session, cs, workers, want = chaos
    # PAGE = the client-side delivered-page pseudo-method: nth counts
    # real page bodies, so the corruption is deterministic
    F.install(F.FaultPlan.parse("client:PAGE:/results/:1:partial"))
    try:
        assert norm(cs.sql(QUERY).rows) == want
        rec = session.last_stats.recovery
        assert rec.get("pages_retried", 0) >= 1, rec
        assert "query_retries" not in rec, rec
    finally:
        _reset(session, cs, workers)


def test_corrupt_json_range_sample_page_reverified(chaos):
    """ISSUE-3 satellite: page verification is gated on the DECLARED
    page encoding (X-Page-Encoding), not the PTPG magic sniff — a
    corrupted JSON range-sample page (which has no magic and used to
    pass through unverified, poisoning the splitter computation) now
    fails the parse check on receipt and is re-requested by token."""
    session, cs, workers, want = chaos
    q = ("SELECT c_custkey, c_acctbal FROM customer "
         "ORDER BY c_acctbal DESC, c_custkey")
    session.properties["distributed_sort_threshold_rows"] = 100
    # bucket 2 (= out_buckets with 2 workers) is the range side channel
    # carrying the JSON key sample; corrupt the first delivered copy
    F.install(F.FaultPlan.parse("client:PAGE:/results/2/:1:partial"))
    try:
        want_sorted = norm(session.sql(q).rows)
        assert norm(cs.sql(q).rows) == want_sorted
        rec = session.last_stats.recovery
        assert rec.get("pages_retried", 0) >= 1, rec
        assert "query_retries" not in rec, rec
    finally:
        session.properties.pop("distributed_sort_threshold_rows", None)
        _reset(session, cs, workers)


def test_connection_reset_absorbed_while_worker_healthy(chaos):
    """A scripted connection reset is absorbed by the poll loop: the
    circuit breaker probes the worker, finds it healthy, and the pull
    continues — no quarantine, no query retry."""
    session, cs, workers, want = chaos
    F.install(F.FaultPlan.parse("client:GET:/results/:1:reset"))
    try:
        assert norm(cs.sql(QUERY).rows) == want
        rec = session.last_stats.recovery
        assert rec.get("http_retries", 0) >= 1, rec
        assert "workers_quarantined" not in rec, rec
    finally:
        _reset(session, cs, workers)


def test_straggler_hedged_duplicate_wins(chaos):
    """Acceptance: a scripted exec delay makes one leaf task a
    straggler; the hedge monitor re-runs it on the healthy survivor and
    the duplicate's FINISHED wins (dedup by the page-token sequence)."""
    session, cs, workers, want = chaos
    workers[1].faults = F.FaultPlan.parse("exec:EXEC:*:1:delay:8.0")
    try:
        assert norm(cs.sql(QUERY).rows) == want
        rec = session.last_stats.recovery
        assert rec.get("hedges_launched", 0) >= 1, rec
        assert rec.get("hedges_won", 0) >= 1, rec
        assert "query_retries" not in rec, rec
    finally:
        _reset(session, cs, workers)


def test_deadline_expiry_cancels_all_tasks(chaos):
    """Acceptance: when the query-level deadline expires, the
    coordinator aborts and every live worker task observes DELETE —
    asserted synchronously (the reap runs before sql() raises), so no
    sleep-based polling."""
    session, cs, workers, want = chaos
    for w in workers:
        w.faults = F.FaultPlan.parse("exec:EXEC:*:1:delay:30.0")
    session.set("cluster_query_deadline_s", 1.5)
    try:
        with pytest.raises(TimeoutError):
            cs.sql(QUERY)
        rec = session.last_stats.recovery
        assert rec.get("deadline_expired", 0) == 1, rec
        assert rec.get("task_cancels", 0) >= 2, rec
        for w in workers:  # DELETE observed: no orphaned task state
            assert not w.tasks, list(w.tasks)
        assert session.last_stats.state == "FAILED"
    finally:
        _reset(session, cs, workers)


def test_worker_crash_mid_wave_remaps_to_survivors(tpch_catalog_tiny):
    """Acceptance: a scripted worker crash mid-wave trips the circuit
    breaker; the retry remaps the dead slots onto survivors and the
    query succeeds — the crashed worker lands in quarantine, not in an
    endless probe loop.  Task-granular restart is pinned OFF: this
    test exercises the whole-attempt remap path deliberately (the
    in-attempt path has its own test, test_task_crash_reruns_one_slot
    — with restarts on, this crash never escalates to a retry)."""
    session = presto_tpu.connect(tpch_catalog_tiny)
    session.properties["cluster_task_restarts"] = 0
    workers = [C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache",
                              faults=F.FaultPlan([])).start()
               for _ in range(2)]
    cs = C.ClusterSession(session, [w.url for w in workers])
    try:
        want = norm(session.sql(QUERY).rows)
        assert norm(cs.sql(QUERY).rows) == want  # prewarm
        workers[1].faults = F.FaultPlan.parse("exec:EXEC:*:1:crash")
        assert norm(cs.sql(QUERY).rows) == want
        rec = session.last_stats.recovery
        assert rec.get("query_retries", 0) == 1, rec
        assert rec.get("workers_quarantined", 0) >= 1, rec
        assert cs.workers == [workers[0].url]
        assert workers[1].url in cs._benched
        assert workers[1].crashed
    finally:
        for w in workers:
            if not w.crashed:
                w.stop()


# ---- dynamic filtering under faults (ISSUE 5 satellite) ---------------


DF_QUERY = ("SELECT count(*) c, sum(l_extendedprice) s FROM lineitem, "
            "part WHERE p_partkey = l_partkey "
            "AND p_container = 'MED BOX'")


def _df_counters(url):
    import json
    import urllib.request

    req = C._signed_request("GET", f"{url}/v1/info")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())["counters"]


def test_df_push_drop_leaves_probe_filter_free(chaos):
    """A dropped build-summary POST (the /dynfilter side channel) leaves
    the probe running filter-free after its bounded wait: identical
    results, df_filters_applied == 0, and NO query-level retry — an
    undelivered filter is a perf miss, never a failure."""
    session, cs, workers, _want = chaos
    want = norm(session.sql(DF_QUERY).rows)
    session.set("broadcast_join_threshold_rows", 0)  # side-channel shape
    session.set("dynamic_filtering_wait_ms", 300)
    F.install(F.FaultPlan.parse("client:POST:/dynfilter:1+:drop"))
    before = [_df_counters(w.url) for w in workers]
    try:
        assert norm(cs.sql(DF_QUERY).rows) == want
        after = [_df_counters(w.url) for w in workers]
        applied = sum(a["df_filters_applied"] - b["df_filters_applied"]
                      for a, b in zip(after, before))
        pruned = sum(a["df_rows_pruned"] - b["df_rows_pruned"]
                     for a, b in zip(after, before))
        assert applied == 0 and pruned == 0, (applied, pruned)
        rec = session.last_stats.recovery
        assert "query_retries" not in rec, rec
        assert "deadline_expired" not in rec, rec
    finally:
        session.set("broadcast_join_threshold_rows", 1_000_000)
        session.set("dynamic_filtering_wait_ms", 0)
        _reset(session, cs, workers)


def test_df_build_crash_degrades_filter_free(tpch_catalog_tiny):
    """A build-side worker crash mid-query: the probe never stalls on
    the filter (wait budget 0), the retry remaps to the survivor — ONE
    query retry, no storm — and results are identical with
    df_filters_applied == 0 on the surviving worker."""
    session = presto_tpu.connect(tpch_catalog_tiny)
    session.set("broadcast_join_threshold_rows", 0)  # side-channel shape
    workers = [C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache",
                              faults=F.FaultPlan([])).start()
               for _ in range(2)]
    cs = C.ClusterSession(session, [w.url for w in workers])
    try:
        want = norm(session.sql(DF_QUERY).rows)
        assert norm(cs.sql(DF_QUERY).rows) == want  # prewarm
        before = _df_counters(workers[0].url)
        workers[1].faults = F.FaultPlan.parse("exec:EXEC:*:1:crash")
        assert norm(cs.sql(DF_QUERY).rows) == want
        rec = session.last_stats.recovery
        assert rec.get("query_retries", 0) == 1, rec
        assert "deadline_expired" not in rec, rec
        after = _df_counters(workers[0].url)
        assert after["df_filters_applied"] == \
            before["df_filters_applied"], (before, after)
        assert workers[1].crashed
    finally:
        for w in workers:
            if not w.crashed:
                w.stop()


# ---- fragment fusion under faults (ISSUE 8 satellite) -----------------


FUSE_QUERY = ("SELECT o_orderpriority, count(*) c, "
              "checksum(o_orderkey) k FROM orders "
              "GROUP BY o_orderpriority ORDER BY 1")


def test_fused_task_fault_degrades_to_fragment_path(tpch_catalog_tiny):
    """A scripted failure INSIDE a fused super-fragment degrades to the
    per-fragment HTTP path: one unfused retry, identical checksums, and
    fragments_fused == 0 on the successful attempt."""
    session = presto_tpu.connect(tpch_catalog_tiny)
    want = norm(session.sql(FUSE_QUERY).rows)
    w = C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache", mesh_devices=4,
                       faults=F.FaultPlan.parse("exec:EXEC:*:1:fail")
                       ).start()
    cs = C.ClusterSession(session, [w.url])
    try:
        r = cs.sql(FUSE_QUERY)
        assert norm(r.rows) == want
        st = r.stats
        assert st.fragments_fused == 0, "retry must run unfused"
        rec = st.recovery
        assert rec.get("fused_fallbacks", 0) == 1, rec
        assert rec.get("query_retries", 0) == 1, rec
        assert len(w.faults.fired) == 1  # the fault hit the fused task
        # the retry really took the HTTP fragment path
        assert st.exchange_bytes_host > 0
    finally:
        w.stop()


@pytest.mark.slow
def test_fused_worker_crash_degrades_to_survivor(tpch_catalog_tiny):
    """The mesh owner crashes mid-fused-task: the retry runs the
    fragment-cut path on the (meshless) survivor with identical
    checksums and fragments_fused == 0.  (Tier-2: the injected-fault
    variant above covers the tier-1 degrade contract.)"""
    session = presto_tpu.connect(tpch_catalog_tiny)
    want = norm(session.sql(FUSE_QUERY).rows)
    meshy = C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache",
                           mesh_devices=4,
                           faults=F.FaultPlan.parse("exec:EXEC:*:1:crash")
                           ).start()
    plain = C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache").start()
    cs = C.ClusterSession(session, [meshy.url, plain.url])
    try:
        r = cs.sql(FUSE_QUERY)
        assert norm(r.rows) == want
        st = r.stats
        assert st.fragments_fused == 0
        assert st.recovery.get("fused_fallbacks", 0) == 1, st.recovery
        assert meshy.crashed
        assert cs.workers == [plain.url]
    finally:
        for w in (meshy, plain):
            if not w.crashed:
                w.stop()


# ---- trace propagation under chaos (ISSUE 9 satellite) ----------------


def _assert_one_well_formed_trace(st):
    """Every recorded span carries the query's trace id and every
    parent resolves inside the merged set (or is the root)."""
    spans = st.trace_spans or []
    assert spans
    assert {d["trace_id"] for d in spans} == {st.trace_id}
    ids = {d["span_id"] for d in spans}
    for d in spans:
        assert d["parent_id"] == "" or d["parent_id"] in ids, d
    return spans


@pytest.mark.slow
def test_hedged_straggler_yields_one_trace_with_loser_marked(chaos):
    """The hedged run produces a SINGLE well-formed trace: the hedge
    attempt is its own span (hedge-monitor lane) whose args mark the
    losing task, and the winner's worker-side task span is merged.
    (Tier-2: the scripted 8s straggler delay is real wall time; the
    tier-1 dropped-header test covers the degrade contract and
    test_straggler_hedged_duplicate_wins covers hedging itself.)"""
    session, cs, workers, want = chaos
    workers[1].faults = F.FaultPlan.parse("exec:EXEC:*:1:delay:8.0")
    try:
        r = cs.sql(QUERY)
        assert norm(r.rows) == want
        assert r.stats.recovery.get("hedges_won", 0) >= 1
        spans = _assert_one_well_formed_trace(r.stats)
        hedges = [d for d in spans if d["kind"] == "attempt"
                  and d["name"].startswith("hedge")]
        assert hedges, [d["name"] for d in spans]
        h = hedges[0]
        assert h["args"].get("lost") and h["args"].get("won"), h
        assert h["args"]["lost"] != h["args"]["won"]
        # the winning attempt's worker task span made it into the trace
        won = h["args"]["won"]
        assert any(d["kind"] == "task" and d["args"].get("task_id") == won
                   for d in spans), won
    finally:
        _reset(session, cs, workers)


@pytest.mark.slow
def test_crash_remap_yields_one_well_formed_trace(tpch_catalog_tiny):
    """A worker crash + query retry still merges into ONE well-formed
    trace (second-attempt task spans under the same trace id); spans
    from the crashed worker are simply absent, never an error.
    (Tier-2: spins its own 2-worker cluster + prewarm.  Task-granular
    restart pinned OFF — this exercises the whole-attempt retry
    trace.)"""
    session = presto_tpu.connect(tpch_catalog_tiny)
    session.properties["cluster_task_restarts"] = 0
    workers = [C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache",
                              faults=F.FaultPlan([])).start()
               for _ in range(2)]
    cs = C.ClusterSession(session, [w.url for w in workers])
    try:
        want = norm(session.sql(QUERY).rows)
        assert norm(cs.sql(QUERY).rows) == want  # prewarm
        workers[1].faults = F.FaultPlan.parse("exec:EXEC:*:1:crash")
        r = cs.sql(QUERY)
        assert norm(r.rows) == want
        assert r.stats.recovery.get("query_retries", 0) == 1
        spans = _assert_one_well_formed_trace(r.stats)
        assert any(d["kind"] == "task" for d in spans)
        # every merged task span came from the surviving worker
        lanes = {d["lane"] for d in spans if d["kind"] == "task"}
        assert lanes == {f"worker:{workers[0].port}"}, lanes
    finally:
        for w in workers:
            if not w.crashed:
                w.stop()


def test_dropped_trace_header_degrades_to_worker_local(chaos,
                                                       monkeypatch):
    """PRESTO_TPU_TRACE_PROPAGATION=off strips the X-Presto-Trace
    header: workers record worker-LOCAL traces (fresh trace ids), the
    coordinator's merge refuses and counts them, the query succeeds,
    and the coordinator-side trace stays well-formed."""
    session, cs, workers, want = chaos
    monkeypatch.setenv("PRESTO_TPU_TRACE_PROPAGATION", "off")
    try:
        r = cs.sql(QUERY)
        assert norm(r.rows) == want
        st = r.stats
        spans = _assert_one_well_formed_trace(st)
        assert {d["lane"] for d in spans} == {"coordinator"}
        assert st.trace_spans_dropped >= 1
        # the worker really did record a LOCAL trace of its own
        locals_ = [w.last_task_spans for w in workers
                   if getattr(w, "last_task_spans", None)]
        assert locals_
        for wspans in locals_:
            assert all(d["trace_id"] != st.trace_id for d in wspans)
            assert any(d["args"].get("local_trace") for d in wspans
                       if d["kind"] == "task")
    finally:
        _reset(session, cs, workers)


def test_env_fault_plan_roundtrip(monkeypatch):
    monkeypatch.setenv("PRESTO_TPU_FAULTS",
                       "server:GET:/results/:3:drop;exec:EXEC:*:1:fail")
    p = F.FaultPlan.from_env()
    assert [r.action for r in p.rules] == ["drop", "fail"]
    w = object.__new__(C.WorkerServer)  # no bind: just the env pickup
    w.faults = F.FaultPlan.from_env()
    assert len(w.faults.rules) == 2


# ---- coordinator crash (ISSUE 16): fleet failover ---------------------


def test_coordinator_crash_failover_reclaim_and_orphan_reap(chaos):
    """A coordinator dies mid-query: its worker slot leases are
    reclaimed in one directory sweep, the task it never DELETEd is
    reaped by the worker's deadline+grace sweep (buffers freed exactly
    like an explicit DELETE), and the SURVIVOR coordinator serves the
    retried submit with an identical checksum — zero wrong results,
    zero leaked worker tasks."""
    import time as _time
    import urllib.request as _rq

    from presto_tpu.server import fleet as FL

    session, cs, workers, want = chaos
    d = FL.FleetDirectory()
    ma = d.join("A", "http://a.invalid")
    mb = d.join("B", "http://b.invalid")
    for w in workers:
        d.slots.register_worker(w.url, 4)
    # A was mid-query at the crash: it holds one slot lease per worker
    # and left a buffered task on worker 0 it will never DELETE
    assert ma.lease_slot(workers[0].url)
    assert ma.lease_slot(workers[1].url)
    w0 = workers[0]
    page = b"x" * 4096
    with w0.lock:
        w0.tasks["q-dead-A.0.0"] = {
            "state": "RUNNING", "error": None,
            "pages": {0: [(page, C.PAGE_ENC_PTPG)]}, "complete": True,
            "range_boundaries": None, "range_event": None,
            "expires_at": _time.monotonic() - 1.0,  # deadline long past
            "dynfilters": {}, "df_event": None,
            "lease_coord": "A"}  # slot-lease provenance tag (ISSUE 17)
        w0.counters["buffered_bytes"] += len(page)
        buffered_before = w0.counters["buffered_bytes"]
        reaped_before = w0.counters["tasks_reaped"]
    # the crash: heartbeat failure -> directory.leave (discovery's
    # watch_fleet path) -> BOTH leases reclaimed in one sweep
    assert d.leave("A") == 2
    assert d.slots.stats()["inFlight"] == 0
    assert d.slots.stats()["leasesReclaimed"] == 2
    # the worker's opportunistic sweep (rides /v1/info) reaps the
    # orphan and frees its page buffer; its lease-release of the tag is
    # a no-op here — the directory sweep got there first, and a double
    # release must never over-count (ISSUE 17 satellite)
    w0.lease_board = d.slots
    try:
        info = _rq.urlopen(w0.url + "/v1/info", timeout=30).read()
    finally:
        w0.lease_board = None
    assert b"tasks_reaped" in info
    with w0.lock:
        assert "q-dead-A.0.0" not in w0.tasks
        assert w0.counters["tasks_reaped"] == reaped_before + 1
        assert w0.counters["buffered_bytes"] == buffered_before - len(page)
    assert d.slots.stats()["leasesReclaimed"] == 2  # double release no-ops
    # the survivor serves the retried submit over the same fleet —
    # identical checksum, leases cycle back to zero, no task residue
    cb = C.ClusterSession(session, [w.url for w in workers], fleet=mb)
    r = cb.sql(QUERY)
    assert norm(r.rows) == want
    st = d.slots.stats()
    assert st["inFlight"] == 0 and st["leasesGranted"] > 2
    for w in workers:
        with w.lock:
            assert not w.tasks  # survivor DELETEd everything it made


# ---- fault-tolerant execution (ISSUE 17) ------------------------------


def test_task_crash_reruns_one_slot(chaos):
    """Acceptance (1): ONE task fails mid-wave -> only that slot re-runs
    on the healthy survivor inside the SAME attempt.  tasks_rerun == 1,
    zero query-level retries, zero quarantines (the worker is healthy —
    only its task died), and the fleet-wide `executed` delta equals the
    clean run's: the failed exec never counted, its rerun adds the one
    back, and completed siblings are never re-executed."""
    session, cs, workers, want = chaos
    try:
        base = [_df_counters(w.url) for w in workers]
        assert norm(cs.sql(QUERY).rows) == want  # clean-run delta
        mid = [_df_counters(w.url) for w in workers]
        clean = sum(a["executed"] - b["executed"]
                    for a, b in zip(mid, base))
        assert clean >= 2
        workers[1].faults = F.FaultPlan.parse("exec:EXEC:*:1:fail")
        assert norm(cs.sql(QUERY).rows) == want
        rec = session.last_stats.recovery
        assert rec.get("tasks_rerun", 0) == 1, rec
        assert "query_retries" not in rec, rec
        assert "workers_quarantined" not in rec, rec
        after = [_df_counters(w.url) for w in workers]
        fault = sum(a["executed"] - b["executed"]
                    for a, b in zip(after, mid))
        assert fault == clean, (fault, clean)
        for w in workers:  # original AND rerun both DELETEd
            assert not w.tasks, list(w.tasks)
    finally:
        _reset(session, cs, workers)


def test_journal_write_fault_degrades_to_journalless(chaos, tmp_path):
    """Fault surface: a failed journal write NEVER fails the query — it
    degrades to journal-less execution (no `journal_writes` recovery
    counter, no entry on disk, identical results)."""
    import os as _os

    from presto_tpu.parallel import journal as J

    session, cs, workers, want = chaos
    keys = ("query_journal", "query_journal_path",
            "recoverable_grouped_execution")
    saved = {k: session.properties.get(k) for k in keys}
    session.properties["query_journal"] = True
    session.properties["query_journal_path"] = str(tmp_path)
    session.properties["recoverable_grouped_execution"] = True
    F.install(F.FaultPlan.parse("journal:WRITE:*:1+:fail"))
    try:
        assert norm(cs.sql(QUERY).rows) == want
        rec = session.last_stats.recovery
        assert "journal_writes" not in rec, rec
        assert "query_retries" not in rec, rec
        assert not any(n.endswith(J.SUFFIX)
                       for n in _os.listdir(tmp_path))
    finally:
        session.properties.update(saved)
        _reset(session, cs, workers)


def test_coordinator_death_adoption_replays_journal(chaos, tmp_path,
                                                    tpch_catalog_tiny):
    """Acceptance (2): coordinator A dies with an in-flight journaled
    query; the ring successor B adopts it and the query completes with
    a checksum identical to the fault-free run, `queries_adopted >= 1`,
    worker 0's completed durable pages REPLAYED (not re-executed), only
    the lost task re-run, zero leaked worker tasks, and the journal
    entry retired."""
    import os as _os

    from presto_tpu.server import fleet as FL

    session, cs, workers, want = chaos
    _reset(session, cs, workers)
    props = {"spill_path": str(tmp_path / "spill"),
             "query_journal_path": str(tmp_path / "journal"),
             "cluster_query_retries": 0,
             "cluster_task_restarts": 0}
    d = FL.FleetDirectory()
    ma = d.join("A", "http://a.invalid")
    mb = d.join("B", "http://b.invalid")
    for w in workers:
        d.slots.register_worker(w.url, 8)
    sa = presto_tpu.connect(tpch_catalog_tiny)
    sa.properties.update(props)
    ca = C.ClusterSession(sa, [w.url for w in workers], fleet=ma)
    ca._journal_keep = True  # A dies before its cleanup runs
    workers[1].faults = F.FaultPlan.parse("exec:EXEC:*:1:fail")
    try:
        with pytest.raises(C.UpstreamFailed):
            ca.sql(QUERY)
        assert sa.last_stats.recovery.get("journal_writes", 0) >= 1
        jroot = str(tmp_path / "journal")
        assert len(_os.listdir(jroot)) == 1  # the entry outlived A
        # the failure detector's verdict: A leaves; B is the successor
        d.leave("A")
        assert mb.should_adopt("A")
        workers[1].faults = F.FaultPlan([])
        sb = presto_tpu.connect(tpch_catalog_tiny)
        sb.properties.update(props)
        cb = C.ClusterSession(sb, [w.url for w in workers], fleet=mb)
        pre = [_df_counters(w.url) for w in workers]
        out = cb.adopt_journaled("A")
        assert len(out) == 1
        _qid, res = out[0]
        assert not isinstance(res, Exception), res
        assert norm(res.rows) == want  # checksum identical
        rec = sb.last_stats.recovery
        assert rec.get("queries_adopted", 0) == 1, rec
        assert rec.get("adoption_ms", 0) >= 1, rec
        post = [_df_counters(w.url) for w in workers]
        # the survivor's completed durable pages replayed from disk...
        assert post[0]["replayed"] - pre[0]["replayed"] == 1
        assert post[0]["executed"] - pre[0]["executed"] == 0
        # ...and only the dead coordinator's lost work re-executed
        assert post[1]["executed"] - pre[1]["executed"] == 1
        assert _os.listdir(jroot) == []  # entry retired by the adopter
        for w in workers:  # zero leaked worker tasks
            assert not w.tasks, list(w.tasks)
    finally:
        _reset(session, cs, workers)


def test_fused_attempt_crash_adopter_replays_fused_pages(
        tpch_catalog_tiny, tmp_path):
    """Satellite (ISSUE 17): fused attempts participate in durable
    replay.  The durable key is content-addressed on the POST-fusion
    fragment serde, so when the coordinator dies AFTER the fused task
    completed (its results pull never succeeds), the adopter's
    force-fused resume REPLAYS the fused task's durable pages instead
    of re-executing them — and a fused root's key can never alias a cut
    fragment's (different serde bytes)."""
    from presto_tpu.server import fleet as FL

    props = {"fragment_fusion": "force",
             "spill_path": str(tmp_path / "spill"),
             "query_journal_path": str(tmp_path / "journal"),
             "cluster_query_retries": 0,
             "cluster_task_restarts": 0}
    session = presto_tpu.connect(tpch_catalog_tiny)
    want = norm(session.sql(FUSE_QUERY).rows)
    meshy = C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache",
                           mesh_devices=4).start()
    plain = C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache").start()
    d = FL.FleetDirectory()
    ma = d.join("A", "http://a.invalid")
    mb = d.join("B", "http://b.invalid")
    for w in (meshy, plain):
        d.slots.register_worker(w.url, 8)
    sa = presto_tpu.connect(tpch_catalog_tiny)
    sa.properties.update(props)
    ca = C.ClusterSession(sa, [meshy.url, plain.url], fleet=ma)
    ca._journal_keep = True
    try:
        # the fused task executes and durably publishes; the coordinator
        # "dies" consuming its DELIVERED pages (the PAGE pseudo-method
        # fires only on 200-with-body responses, so the fused task has
        # demonstrably completed + durably published each faulted page
        # — a plain GET rule would race the producer and cancel the
        # fused task mid-execution; 500s are bounded by the retry
        # budget, unlike resets, which the pull loop absorbs while the
        # worker's health probes keep succeeding)
        F.install(F.FaultPlan.parse("client:PAGE:/results/:1+:http500"))
        with pytest.raises(C.UpstreamFailed):
            ca.sql(FUSE_QUERY)
        F.install(None)
        assert meshy.counters["tasks_fused"] >= 1  # it really fused
        d.leave("A")
        sb = presto_tpu.connect(tpch_catalog_tiny)
        sb.properties.update(props)
        cb = C.ClusterSession(sb, [meshy.url, plain.url], fleet=mb)
        pre = _df_counters(meshy.url)
        out = cb.adopt_journaled("A")
        assert len(out) == 1
        _qid, res = out[0]
        assert not isinstance(res, Exception), res
        assert norm(res.rows) == want
        post = _df_counters(meshy.url)
        assert post["replayed"] - pre["replayed"] >= 1
        assert post["executed"] - pre["executed"] == 0  # no re-execution
        assert sb.last_stats.recovery.get("queries_adopted", 0) == 1
    finally:
        F.install(None)
        for w in (meshy, plain):
            if not w.crashed:
                w.stop()


def test_worker_reap_releases_held_lease_tags(chaos):
    """Satellite (ISSUE 17): reap_expired releases a reaped orphan's
    still-held slot-lease tag immediately (SlotLeaseBoard.reclaim_task)
    instead of waiting for the directory's dead-coordinator sweep —
    tasks_reaped and leases_reclaimed agree, and the later sweep finds
    nothing left to reclaim."""
    import time as _time

    from presto_tpu.server import fleet as FL

    session, cs, workers, want = chaos
    d = FL.FleetDirectory()
    ma = d.join("A", "http://a.invalid")
    w0 = workers[0]
    d.slots.register_worker(w0.url, 4)
    w0.lease_board = d.slots
    try:
        assert ma.lease_slot(w0.url)
        reaped0 = w0.counters["tasks_reaped"]
        with w0.lock:
            w0.tasks["q-lease-A.0.0"] = {
                "state": "RUNNING", "error": None, "pages": {},
                "complete": True, "range_boundaries": None,
                "range_event": None,
                "expires_at": _time.monotonic() - 1.0,
                "dynfilters": {}, "df_event": None,
                "lease_coord": "A"}
        assert w0.reap_expired() == 1
        st = d.slots.stats()
        assert st["inFlight"] == 0
        assert st["leasesReclaimed"] == 1
        assert w0.counters["tasks_reaped"] - reaped0 == \
            st["leasesReclaimed"]
        # the directory sweep afterwards has nothing left to reclaim
        assert d.leave("A") == 0
        assert d.slots.stats()["leasesReclaimed"] == 1
    finally:
        w0.lease_board = None
        _reset(session, cs, workers)
