"""t-digest + P4HyperLogLog sketch family (VERDICT r4 item 8).

Reference: presto-main/.../operator/aggregation/tdigest/TDigest.java +
TDigestAggregationFunction, spi/type/P4HyperLogLogType; sketches CAST
to/from VARBINARY and merge across partitions/the mesh.
"""

import numpy as np
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.catalog import Catalog
from presto_tpu.functions import tdigest as TD

N = 20_000


def _session():
    rng = np.random.RandomState(7)
    cat = Catalog()
    cat.register_memory("t", {"g": T.BIGINT, "x": T.DOUBLE},
                        {"g": np.arange(N, dtype=np.int64) % 4,
                         "x": rng.lognormal(0.0, 1.0, N)})
    return presto_tpu.connect(cat), rng


def test_tdigest_agg_quantiles_accurate():
    s, rng = _session()
    r = s.sql("SELECT value_at_quantile(tdigest_agg(x), 0.5), "
              "value_at_quantile(tdigest_agg(x), 0.99) FROM t").rows[0]
    data = np.random.RandomState(7).lognormal(0.0, 1.0, N)
    for est, q in zip(r, (0.5, 0.99)):
        rank = (data <= est).mean()
        assert abs(rank - q) < 0.01, (q, est, rank)


def test_tdigest_group_by_and_values_at_quantiles():
    s, _ = _session()
    r = s.sql("SELECT g, values_at_quantiles(tdigest_agg(x), "
              "ARRAY[0.25, 0.5, 0.75]) FROM t GROUP BY g ORDER BY g")
    assert len(r.rows) == 4
    for _g, vals in r.rows:
        assert len(vals) == 3 and vals[0] < vals[1] < vals[2]


def test_tdigest_merge_equals_single_build():
    s, _ = _session()
    merged = s.sql("SELECT value_at_quantile(merge(d), 0.5) FROM "
                   "(SELECT tdigest_agg(x) d FROM t GROUP BY g)"
                   ).rows[0][0]
    single = s.sql("SELECT value_at_quantile(tdigest_agg(x), 0.5) "
                   "FROM t").rows[0][0]
    assert abs(merged - single) / single < 0.05


def test_tdigest_varbinary_roundtrip():
    s, _ = _session()
    r = s.sql("SELECT value_at_quantile(CAST(CAST(tdigest_agg(x) AS "
              "VARBINARY) AS TDIGEST(DOUBLE)), 0.5) FROM t").rows[0][0]
    direct = s.sql("SELECT value_at_quantile(tdigest_agg(x), 0.5) "
                   "FROM t").rows[0][0]
    assert r == direct


def test_tdigest_weighted():
    s, _ = _session()
    # weight 0 rows must not contribute: weight by (g = 0)
    r = s.sql("SELECT value_at_quantile("
              "tdigest_agg(x, CASE WHEN g = 0 THEN 1 ELSE 0 END), 0.5) "
              "FROM t").rows[0][0]
    only_g0 = s.sql("SELECT value_at_quantile(tdigest_agg(x), 0.5) "
                    "FROM t WHERE g = 0").rows[0][0]
    assert abs(r - only_g0) / only_g0 < 0.05


def test_quantile_at_value_and_scale():
    s, _ = _session()
    med, qav = s.sql(
        "SELECT value_at_quantile(d, 0.5), quantile_at_value(d, 1.0) "
        "FROM (SELECT tdigest_agg(x) d FROM t)").rows[0]
    assert 0.3 < qav < 0.7  # lognormal(0,1): P(x <= 1) = 0.5
    scaled = s.sql("SELECT value_at_quantile(scale_tdigest("
                   "tdigest_agg(x), 4.0), 0.5) FROM t").rows[0][0]
    assert abs(scaled - med) / med < 0.01  # scaling preserves quantiles


def test_destructure_tdigest():
    s, _ = _session()
    row = s.sql("SELECT destructure_tdigest(tdigest_agg(x)) FROM t"
                ).rows[0][0]
    means, weights, compression, mn, mx, total = row
    assert len(means) == len(weights) and len(means) > 10
    assert compression == 100.0 and mn < mx and total == N


def test_p4hll_type_and_casts():
    s, _ = _session()
    card = s.sql("SELECT cardinality(CAST(approx_set(g) AS "
                 "P4HYPERLOGLOG)) FROM t").rows[0][0]
    assert card == 4
    # VARBINARY round-trip through P4HLL
    r = s.sql("SELECT cardinality(CAST(CAST(approx_set(x) AS VARBINARY)"
              " AS P4HYPERLOGLOG)) FROM t").rows[0][0]
    assert abs(r - N) / N < 0.1
    # merge() over P4HLL
    r = s.sql("SELECT cardinality(merge(h)) FROM (SELECT CAST("
              "approx_set(x) AS P4HYPERLOGLOG) h FROM t GROUP BY g)"
              ).rows[0][0]
    assert abs(r - N) / N < 0.1


def test_tdigest_mesh_partition_merge():
    """Distributed-merge semantics: per-partition digests built
    independently (the mesh/cluster partial-aggregation shape) merge to
    the same answer as a single build — host-level check of the wire
    contract."""
    rng = np.random.RandomState(3)
    data = rng.normal(50, 10, 100_000)
    shards = np.array_split(data, 8)  # 8 "devices"
    blobs = [TD.tdigest_from_values(s) for s in shards]
    merged = TD.tdigest_merge(blobs)
    for q in (0.1, 0.5, 0.9):
        est = TD.tdigest_value_at_quantile(merged, q)
        rank = (data <= est).mean()
        assert abs(rank - q) < 0.01


def test_tdigest_empty_and_null_inputs():
    s, _ = _session()
    assert s.sql("SELECT value_at_quantile(tdigest_agg(x), 0.5) "
                 "FROM t WHERE x > 1e18").rows[0][0] is None
    r = s.sql("SELECT value_at_quantile(tdigest_agg(y), 0.5) FROM "
              "(VALUES (1.0), (CAST(NULL AS DOUBLE)), (3.0)) v(y)"
              ).rows[0][0]
    assert 1.0 <= r <= 3.0


def test_sketch_base64_export_reimport_across_queries():
    # the persist/merge-later workflow: export in one query, reimport
    # in another (reference: casting sketches through varbinary)
    s, _ = _session()
    blob = s.sql("SELECT to_base64(CAST(approx_set(g) AS VARBINARY)) "
                 "FROM t").rows[0][0]
    r = s.sql(f"SELECT cardinality(CAST(from_base64('{blob}') AS "
              "P4HYPERLOGLOG))").rows
    assert r == [(4,)]
