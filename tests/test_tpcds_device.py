"""Device-side TPC-DS fact generation must match the host generator
column-for-column (same splitmix64 counters; see
presto_tpu/connectors/tpcds_device.py), and the chunk grids must
partition sales AND returns rows exactly."""

import numpy as np
import pytest

from presto_tpu.connectors import tpcds as DS
from presto_tpu.connectors import tpcds_device as D

SF = 0.02


@pytest.mark.parametrize("table", sorted(D.DEVICE_COLUMNS))
def test_device_matches_host(table):
    cols = sorted(D.DEVICE_COLUMNS[table])
    n = DS.row_count(table, SF)
    host = DS.generate(table, SF, 0, n)
    dev = D.generate_device(table, SF, cols, 0, n)
    for c in cols:
        got = np.asarray(dev[c].data)
        want = np.asarray(host[c])
        assert got.shape == want.shape, (c, got.shape, want.shape)
        if np.issubdtype(want.dtype, np.floating):
            np.testing.assert_allclose(got, want, rtol=0, atol=0,
                                       err_msg=c)
        else:
            assert (got == want).all(), (c, got[:5], want[:5])


def test_traced_row0_padding():
    """Chunk-mode generation: traced start + static pad serves every
    chunk; live rows match the host."""
    import jax
    import jax.numpy as jnp

    cols = ["ss_item_sk", "ss_ticket_number", "ss_ext_list_price"]
    pad = 1000

    @jax.jit
    def gen(row0):
        raw = D.generate_device("store_sales", SF, cols, row0, pad)
        return {c: raw[c].data for c in cols}

    for row0, live in ((0, 1000), (2_997, 1000), (57_000, 404)):
        out = gen(jnp.asarray(row0, jnp.int64))
        host = DS.generate("store_sales", SF, row0, row0 + live)
        for c in cols:
            got = np.asarray(out[c])[:live]
            want = np.asarray(host[c])
            np.testing.assert_array_equal(got, want, err_msg=c)


@pytest.mark.parametrize("fam_table", ["store_sales", "catalog_sales"])
def test_chunk_grid_partitions_exactly(fam_table):
    """Edges align to ticket/order units; sales and returns ranges
    partition their tables; every return's bucket value falls inside
    its chunk's sales bucket range (the colocation property)."""
    fam = D.chunk_family(fam_table, SF)

    class S:
        properties = {"chunk_fact_rows": 10_000}

    grid = fam.make_grid(S())
    total_s = DS.row_count(fam.sales, SF)
    total_r = DS.row_count(fam.returns, SF)
    assert grid.edges[0] == 0 and grid.edges[-1] == total_s
    assert grid.ret_edges[0] == 0 and grid.ret_edges[-1] == total_r
    assert all(a < b for a, b in zip(grid.edges[:-1], grid.edges[1:]))
    assert all(e % fam.unit == 0 for e in grid.edges[1:-1])
    bcol_s = fam.bucket_column(fam.sales)
    bcol_r = fam.bucket_column(fam.returns)
    for i in range(grid.nchunks):
        a, b = grid.edges[i], grid.edges[i + 1]
        ra, rb = grid.ret_edges[i], grid.ret_edges[i + 1]
        if ra == rb:
            continue
        s = DS.generate(fam.sales, SF, a, b)
        r = DS.generate(fam.returns, SF, ra, rb)
        s_buckets = set(np.unique(s[bcol_s]).tolist())
        r_buckets = set(np.unique(r[bcol_r]).tolist())
        assert r_buckets <= s_buckets, (i, sorted(r_buckets - s_buckets)[:5])


def test_bucketing_spi_wired():
    from presto_tpu.catalog import tpcds_catalog

    cat = tpcds_catalog(SF)
    assert cat.get("store_sales").bucketing().name == "tpcds-store"
    assert cat.get("store_returns").bucketing().name == "tpcds-store"
    assert cat.get("catalog_sales").bucketing().name == "tpcds-catalog"
    assert cat.get("item").bucketing() is None
