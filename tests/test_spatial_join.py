"""Grid-indexed spatial join (VERDICT r4 item 5).

Reference: presto-main/.../operator/SpatialJoinOperator.java +
PagesRTreeIndex.java + sql/planner/optimizations/ExtractSpatialJoins;
here the runtime index is a uniform grid with a device ray-cast exact
pass (P.SpatialJoin docstring).  Correctness is checked against numpy
brute force; the plan must show the GRID-INDEXED path, and a
100k x 10k join must finish in seconds, not the cross product.
"""

import time

import numpy as np

import presto_tpu
from presto_tpu import types as T
from presto_tpu.catalog import Catalog

NP_, NG = 100_000, 10_000


def _catalog(seed=0):
    rng = np.random.RandomState(seed)
    cx, cy = rng.uniform(0, 100, NG), rng.uniform(0, 100, NG)
    half = rng.uniform(0.1, 0.5, NG)
    wkts = np.asarray(
        [f"POLYGON (({x - h} {y - h}, {x + h} {y - h}, {x + h} {y + h}, "
         f"{x - h} {y + h}, {x - h} {y - h}))"
         for x, y, h in zip(cx, cy, half)], dtype=object)
    cat = Catalog()
    cat.register_memory(
        "pts", {"id": T.BIGINT, "x": T.DOUBLE, "y": T.DOUBLE},
        {"id": np.arange(NP_, dtype=np.int64),
         "x": rng.uniform(0, 100, NP_), "y": rng.uniform(0, 100, NP_)})
    cat.register_memory(
        "regions", {"rid": T.BIGINT, "wkt": T.VARCHAR},
        {"rid": np.arange(NG, dtype=np.int64), "wkt": wkts})
    return cat, (cx, cy, half)


def test_explain_shows_grid_indexed_path():
    cat, _ = _catalog()
    s = presto_tpu.connect(cat)
    txt = s.sql(
        "EXPLAIN SELECT count(*) FROM pts, regions "
        "WHERE ST_Contains(ST_GeometryFromText(wkt), ST_Point(x, y))"
    ).rows[0][0]
    assert "SpatialJoin GRID-INDEXED" in txt
    assert "CROSS" not in txt


def test_contains_join_matches_brute_force_and_is_fast():
    cat, (cx, cy, half) = _catalog()
    s = presto_tpu.connect(cat)
    t0 = time.perf_counter()
    n = s.sql("SELECT count(*) FROM pts, regions "
              "WHERE ST_Contains(ST_GeometryFromText(wkt), "
              "ST_Point(x, y))").rows[0][0]
    wall = time.perf_counter() - t0
    assert wall < 60, f"spatial join took {wall:.1f}s"
    # numpy brute force on the axis-aligned squares (exact oracle)
    xs = np.asarray(cat.get("pts").read(["x"])["x"])
    ys = np.asarray(cat.get("pts").read(["y"])["y"])
    expect = 0
    for i in range(0, NP_, 20_000):  # chunked to bound memory
        sl = slice(i, i + 20_000)
        expect += int(((xs[sl, None] >= cx - half)
                       & (xs[sl, None] <= cx + half)
                       & (ys[sl, None] >= cy - half)
                       & (ys[sl, None] <= cy + half)).sum())
    assert n == expect


def test_within_and_swapped_sides():
    cat, _ = _catalog()
    s = presto_tpu.connect(cat)
    base = s.sql("SELECT count(*) FROM pts, regions "
                 "WHERE ST_Contains(ST_GeometryFromText(wkt), "
                 "ST_Point(x, y))").rows[0][0]
    within = s.sql("SELECT count(*) FROM pts, regions "
                   "WHERE ST_Within(ST_Point(x, y), "
                   "ST_GeometryFromText(wkt))").rows[0][0]
    swapped = s.sql("SELECT count(*) FROM regions, pts "
                    "WHERE ST_Contains(ST_GeometryFromText(wkt), "
                    "ST_Point(x, y))").rows[0][0]
    assert base == within == swapped


def test_residual_filter_applies():
    cat, _ = _catalog()
    s = presto_tpu.connect(cat)
    both = s.sql("SELECT count(*) FROM pts, regions "
                 "WHERE ST_Contains(ST_GeometryFromText(wkt), "
                 "ST_Point(x, y)) AND rid < 5000 AND id % 2 = 0"
                 ).rows[0][0]
    loose = s.sql("SELECT count(*) FROM pts, regions "
                  "WHERE ST_Contains(ST_GeometryFromText(wkt), "
                  "ST_Point(x, y))").rows[0][0]
    assert 0 < both < loose


def test_distance_join():
    rng = np.random.RandomState(5)
    n = 20_000
    cat = Catalog()
    cat.register_memory("a", {"ax": T.DOUBLE, "ay": T.DOUBLE},
                        {"ax": rng.uniform(0, 10, n),
                         "ay": rng.uniform(0, 10, n)})
    cat.register_memory("b", {"bx": T.DOUBLE, "bv": T.DOUBLE},
                        {"bx": rng.uniform(0, 10, n),
                         "bv": rng.uniform(0, 10, n)})
    s = presto_tpu.connect(cat)
    txt = s.sql("EXPLAIN SELECT count(*) FROM a, b WHERE "
                "ST_Distance(ST_Point(ax, ay), ST_Point(bx, bv)) < 0.02"
                ).rows[0][0]
    assert "SpatialJoin GRID-INDEXED" in txt
    got = s.sql("SELECT count(*) FROM a, b WHERE "
                "ST_Distance(ST_Point(ax, ay), ST_Point(bx, bv)) < 0.02"
                ).rows[0][0]
    ax = np.asarray(cat.get("a").read(["ax"])["ax"])
    ay = np.asarray(cat.get("a").read(["ay"])["ay"])
    bx = np.asarray(cat.get("b").read(["bx"])["bx"])
    bv = np.asarray(cat.get("b").read(["bv"])["bv"])
    expect = 0
    for i in range(0, n, 4000):
        sl = slice(i, i + 4000)
        d2 = (ax[sl, None] - bx) ** 2 + (ay[sl, None] - bv) ** 2
        expect += int((d2 < 0.02 ** 2).sum())
    assert got == expect


def test_nonconvex_polygon_with_hole():
    # concave L-shape and a donut: vertex-level grid candidates must
    # still resolve through the exact even-odd ray cast
    cat = Catalog()
    cat.register_memory("p", {"x": T.DOUBLE, "y": T.DOUBLE},
                        {"x": np.asarray([1.0, 3.0, 5.0, 2.5]),
                         "y": np.asarray([1.0, 3.0, 5.0, 2.5])})
    wkts = np.asarray([
        # L-shape: contains (1,1), not (3,3)
        "POLYGON ((0 0, 4 0, 4 2, 2 2, 2 4, 0 4, 0 0))",
        # donut around (2.5, 2.5): ring contains boundary box minus hole
        "POLYGON ((1 1, 4 1, 4 4, 1 4, 1 1), "
        "(2 2, 3 2, 3 3, 2 3, 2 2))",
    ], dtype=object)
    cat.register_memory("g", {"gid": T.BIGINT, "wkt": T.VARCHAR},
                        {"gid": np.arange(2, dtype=np.int64),
                         "wkt": wkts})
    s = presto_tpu.connect(cat)
    r = s.sql("SELECT gid, x, y FROM p, g WHERE ST_Contains("
              "ST_GeometryFromText(wkt), ST_Point(x, y)) "
              "ORDER BY gid, x").rows
    assert (0, 1.0, 1.0) in r  # L contains (1,1)
    assert (0, 3.0, 3.0) not in r  # concave notch
    assert (1, 3.0, 3.0) in r  # donut ring area
    assert (1, 2.5, 2.5) not in r  # inside the hole


def test_null_and_empty_geometries_match_nothing():
    cat = Catalog()
    cat.register_memory("p", {"x": T.DOUBLE, "y": T.DOUBLE},
                        {"x": np.asarray([1.0, np.nan]),
                         "y": np.asarray([1.0, 1.0])})
    wkts = np.ma.masked_array(
        np.asarray(["POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))",
                    "POLYGON EMPTY", "placeholder"], dtype=object),
        mask=[False, False, True])
    cat.register_memory("g", {"gid": T.BIGINT, "wkt": T.VARCHAR},
                        {"gid": np.arange(3, dtype=np.int64),
                         "wkt": wkts})
    s = presto_tpu.connect(cat)
    r = s.sql("SELECT gid FROM p, g WHERE ST_Contains("
              "ST_GeometryFromText(wkt), ST_Point(x, y))").rows
    # only the real polygon x the real point; NULL wkt and EMPTY match
    # nothing, the NaN point matches nothing
    assert r == [(0,)]


def test_low_cardinality_geometry_column_expands_rows():
    # 1000 build ROWS over 4 distinct geometries: matches must expand
    # per ROW, not per distinct entry
    cat = Catalog()
    cat.register_memory("p", {"x": T.DOUBLE, "y": T.DOUBLE},
                        {"x": np.asarray([0.5]), "y": np.asarray([0.5])})
    wkts = np.asarray(
        [f"POLYGON (({i} 0, {i + 1} 0, {i + 1} 1, {i} 1, {i} 0))"
         for i in range(4)], dtype=object)[np.arange(1000) % 4]
    cat.register_memory("g", {"rid": T.BIGINT, "wkt": T.VARCHAR},
                        {"rid": np.arange(1000, dtype=np.int64),
                         "wkt": wkts})
    s = presto_tpu.connect(cat)
    r = s.sql("SELECT count(*) FROM p, g WHERE ST_Contains("
              "ST_GeometryFromText(wkt), ST_Point(x, y))").rows
    assert r == [(250,)]  # every copy of polygon 0 matches


def test_bbox_skew_outlier_handled():
    # one country-sized polygon among tiny ones must not explode the
    # cell expansion (joins brute-force) and must still match
    rng = np.random.RandomState(9)
    n = 5_000
    tiny = [f"POLYGON (({x} {y}, {x + 0.01} {y}, {x + 0.01} {y + 0.01},"
            f" {x} {y + 0.01}, {x} {y}))"
            for x, y in zip(rng.uniform(0, 100, n),
                            rng.uniform(0, 100, n))]
    big = "POLYGON ((0 0, 100 0, 100 100, 0 100, 0 0))"
    cat = Catalog()
    cat.register_memory("p", {"x": T.DOUBLE, "y": T.DOUBLE},
                        {"x": rng.uniform(1, 99, 2000),
                         "y": rng.uniform(1, 99, 2000)})
    cat.register_memory("g", {"gid": T.BIGINT, "wkt": T.VARCHAR},
                        {"gid": np.arange(n + 1, dtype=np.int64),
                         "wkt": np.asarray(tiny + [big], dtype=object)})
    s = presto_tpu.connect(cat)
    t0 = time.perf_counter()
    r = s.sql("SELECT count(*) FROM p, g WHERE ST_Contains("
              "ST_GeometryFromText(wkt), ST_Point(x, y)) "
              "AND gid = " + str(n)).rows
    assert time.perf_counter() - t0 < 30
    assert r == [(2000,)]  # every point is inside the big polygon
