"""Sketch aggregates (ISSUE 19): APPROX_DISTINCT / APPROX_PERCENTILE /
COUNT|SUM WITH ERROR as mergeable device states.

Error-bound property tests against exact sqlite oracles (the reference's
H2QueryRunner role): HLL relative error stays within 2x the theoretical
standard error at the default register count, KLL percentile rank error
stays within the accuracy knob, across dtypes x null masks x empty x
all-null inputs — and the four execution modes (dynamic / compiled /
chunked / cluster-fused) produce IDENTICAL estimates, because every mode
folds the same splitmix64 value hashes into the same state layout
(exec/kernels.py).

The fused-mesh leg additionally asserts the tentpole economics: a
sketch-only aggregate moves ZERO repartition exchange bytes — its
partial states ride the near-zero sketch lane (lax.pmax for the global
HLL edge) instead of an all_to_all of input rows.
"""

import numpy as np
import pytest

import presto_tpu
from presto_tpu.parallel import cluster as C

# 2x the HLL theoretical std error at m=1024 (1.04/sqrt(m) = 3.25%)
HLL_RELERR = 2 * 1.04 / np.sqrt(1024.0)

# q67-class probe: high-cardinality APPROX_DISTINCT under GROUP BY.
# The key is a raw column so the planner's NDV hint keeps the slot
# capacity far below the single-node register-shrink threshold
# (8192 groups at m=1024) — above it the one-pass kernel trades
# registers for slots and mode-identity intentionally ends
Q67 = ("SELECT l_suppkey AS b, approx_distinct(l_partkey) AS d1, "
       "approx_distinct(l_orderkey) AS d2 FROM lineitem "
       "GROUP BY l_suppkey ORDER BY b")

# dtype sweep: integer key, double measure, date, varchar — plus a
# CASE-masked variant (NULLs interleaved) per column
DISTINCT_COLS = [
    "l_partkey",
    "l_extendedprice",
    "l_shipdate",
    "l_comment",
    "CASE WHEN l_linenumber <= 4 THEN l_partkey END",
]


@pytest.fixture(scope="module")
def s(tpch_catalog_tiny):
    return presto_tpu.connect(tpch_catalog_tiny)


@pytest.fixture(scope="module")
def chunked(tpch_catalog_tiny):
    cs = presto_tpu.connect(tpch_catalog_tiny)
    cs.set("execution_mode", "chunked")
    cs.properties["chunked_rows_threshold"] = 50_000
    cs.properties["chunk_orders"] = 20_000
    return cs


@pytest.fixture(scope="module")
def compiled(tpch_catalog_tiny):
    cs = presto_tpu.connect(tpch_catalog_tiny)
    cs.set("execution_mode", "compiled")
    return cs


@pytest.fixture(scope="module")
def fused_cluster(tpch_catalog_tiny):
    session = presto_tpu.connect(tpch_catalog_tiny)
    w = C.WorkerServer("tpch:0.01:/tmp/presto_tpu_cache",
                       mesh_devices=4).start()
    cs = C.ClusterSession(session, [w.url])
    yield session, cs
    w.stop()


def one(sess, sql):
    rows = sess.sql(sql).rows
    assert len(rows) == 1
    return rows[0][0]


# ---------------------------------------------------------------------------
# HLL error bounds vs the exact oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("col", DISTINCT_COLS)
def test_hll_error_bound_vs_oracle(s, tpch_sqlite_tiny, col):
    exact = tpch_sqlite_tiny.execute(
        f"SELECT count(DISTINCT {col}) FROM lineitem").fetchone()[0]
    est = one(s, f"SELECT approx_distinct({col}) FROM lineitem")
    assert exact > 0
    assert abs(est - exact) <= max(HLL_RELERR * exact, 2.0), \
        f"{col}: est={est} exact={exact}"


def test_hll_grouped_error_bound_vs_oracle(s, tpch_sqlite_tiny):
    oracle = dict(tpch_sqlite_tiny.execute(
        "SELECT l_suppkey, count(DISTINCT l_partkey) FROM lineitem "
        "GROUP BY l_suppkey").fetchall())
    rows = s.sql(
        "SELECT l_suppkey AS b, approx_distinct(l_partkey) "
        "FROM lineitem GROUP BY l_suppkey").rows
    assert len(rows) == len(oracle)
    for b, est in rows:
        exact = oracle[b]
        # small groups sit in the linear-counting regime where the
        # noise is occupancy-Poisson, not relative: floor the bound at
        # 3*sqrt(n) so a 2.5-sigma bucket among 100 doesn't flake
        assert abs(est - exact) <= max(HLL_RELERR * exact,
                                       3 * np.sqrt(exact)), \
            f"bucket {b}: est={est} exact={exact}"


def test_hll_error_argument_narrows(s, tpch_sqlite_tiny):
    """approx_distinct(x, e): a tighter max-standard-error literal buys
    more registers; the estimate stays inside 2x the REQUESTED bound."""
    exact = tpch_sqlite_tiny.execute(
        "SELECT count(DISTINCT l_partkey) FROM lineitem").fetchone()[0]
    est = one(s, "SELECT approx_distinct(l_partkey, 0.01) FROM lineitem")
    assert abs(est - exact) <= max(2 * 0.01 * exact, 2.0)


def test_hll_empty_and_all_null(s):
    assert one(s, "SELECT approx_distinct(l_partkey) FROM lineitem "
               "WHERE l_orderkey < 0") == 0
    assert one(s, "SELECT approx_distinct(CASE WHEN l_orderkey < 0 "
               "THEN l_partkey END) FROM lineitem") == 0


# ---------------------------------------------------------------------------
# KLL percentile rank error vs the exact oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("p", [0.1, 0.5, 0.9])
@pytest.mark.parametrize("col", ["l_extendedprice", "l_partkey"])
def test_percentile_rank_error(s, chunked, tpch_sqlite_tiny, col, p):
    """Rank error |rank(est)/n - p| <= accuracy (2x slack for the
    chunked path's merge levels), for double AND integer inputs, in the
    single-pass mode and the merged-summary (chunked) mode."""
    vals = np.sort(np.asarray([r[0] for r in tpch_sqlite_tiny.execute(
        f"SELECT {col} FROM lineitem").fetchall()], dtype=np.float64))
    n = len(vals)
    for sess, slack in ((s, 0.02), (chunked, 0.03)):
        est = float(one(sess, f"SELECT approx_percentile({col}, {p}) "
                        "FROM lineitem"))
        lo = np.searchsorted(vals, est, side="left")
        hi = np.searchsorted(vals, est, side="right")
        rank_err = min(abs(lo / n - p), abs(hi / n - p))
        assert rank_err <= slack, \
            f"{col} p={p}: est={est} rank_err={rank_err:.4f}"


def test_percentile_masked_empty_null(s):
    # masked input: percentile over the surviving rows only
    r = one(s, "SELECT approx_percentile(CASE WHEN l_linenumber = 1 "
            "THEN l_extendedprice END, 0.5) FROM lineitem")
    assert r is not None
    # empty / all-null inputs yield NULL (ok=False), never a crash
    assert one(s, "SELECT approx_percentile(l_extendedprice, 0.5) "
               "FROM lineitem WHERE l_orderkey < 0") is None
    assert one(s, "SELECT approx_percentile(CASE WHEN l_orderkey < 0 "
               "THEN l_extendedprice END, 0.5) FROM lineitem") is None


def test_percentile_accuracy_knob_sizes_state(chunked):
    """approx_percentile_accuracy resizes the mergeable summary; a
    coarser knob still honors its own (wider) bound."""
    prev = chunked.properties.get("approx_percentile_accuracy", 0.01)
    chunked.properties["approx_percentile_accuracy"] = 0.05
    try:
        est = float(one(chunked, "SELECT approx_percentile("
                        "l_extendedprice, 0.5) FROM lineitem"))
        exact = float(one(chunked, "SELECT approx_percentile("
                          "l_extendedprice, 0.5) FROM lineitem "
                          "WHERE l_orderkey >= 0"))
        # both estimates of the same median: within the coarse bound of
        # each other by the triangle inequality on rank error
        assert est > 0 and exact > 0
    finally:
        chunked.properties["approx_percentile_accuracy"] = prev


# ---------------------------------------------------------------------------
# COUNT/SUM ... WITH ERROR (seeded sample)
# ---------------------------------------------------------------------------


def test_with_error_bounds_vs_oracle(s, tpch_sqlite_tiny):
    exact_cnt, exact_sum = tpch_sqlite_tiny.execute(
        "SELECT count(l_partkey), sum(l_partkey) FROM lineitem").fetchone()
    rows = s.sql("SELECT count(l_partkey) WITH ERROR, "
                 "sum(l_partkey) WITH ERROR FROM lineitem").rows
    est_cnt, est_sum = rows[0]
    # 1-in-8 hash sample over ~60k rows: std err ~1.1%; assert 10%
    assert abs(est_cnt - exact_cnt) <= 0.10 * exact_cnt
    assert abs(est_sum - exact_sum) <= 0.10 * exact_sum


def test_with_error_partition_independent(s, chunked):
    """The sample is value-hash-gated, so the estimate is bit-identical
    no matter how rows are split across shards or chunks."""
    q = ("SELECT count(l_partkey) WITH ERROR, "
         "sum(l_extendedprice) WITH ERROR FROM lineitem")
    assert s.sql(q).rows == chunked.sql(q).rows


# ---------------------------------------------------------------------------
# cross-mode estimate identity + the zero-repartition economics
# ---------------------------------------------------------------------------


def test_q67_identical_across_modes(s, compiled, chunked, fused_cluster):
    """The q67-class high-cardinality APPROX_DISTINCT GROUP BY returns
    the SAME estimates in all four modes: every mode hashes values with
    the same splitmix64 family and folds registers with max — the
    estimate is a pure function of the value set."""
    session, cs = fused_cluster
    base = s.sql(Q67).rows
    assert base, "q67 probe returned no rows"
    assert compiled.sql(Q67).rows == base
    assert chunked.sql(Q67).rows == base
    assert cs.sql(Q67).rows == base


def test_fused_sketch_moves_zero_repartition_bytes(fused_cluster):
    """Tentpole acceptance: on the fused mesh the sketch aggregate's
    merge edge moves NO repartition/collective exchange bytes and no
    host pages — only fixed-width sketch state on the sketch lane (the
    global HLL edge lowers to one lax.pmax)."""
    session, cs = fused_cluster
    for q in ("SELECT approx_distinct(l_partkey) FROM lineitem", Q67):
        cs.sql(q)
        st = session.last_stats
        assert st.fragments_fused >= 1, q
        assert st.exchange_bytes_host == 0, (q, st.exchange_bytes_host)
        assert st.exchange_bytes_collective == 0, \
            (q, st.exchange_bytes_collective)
        assert st.exchange_bytes_sketch > 0, q


def test_prepared_approx_distinct_warm_zero_compiles(compiled):
    compiled.sql("PREPARE adq FROM SELECT approx_distinct(l_partkey) "
                 "FROM lineitem WHERE l_orderkey < ?")
    r1 = compiled.sql("EXECUTE adq USING 30000")
    r2 = compiled.sql("EXECUTE adq USING 60000")
    assert r2.stats.compiles == 0, "warm APPROX_DISTINCT EXECUTE recompiled"
    assert r1.rows != [] and r2.rows != []


def test_rewrite_matches_native_approx_distinct(s, tpch_sqlite_tiny):
    """prefer_approx_distinct: the opt-in rewrite plans the SAME sketch
    as a native approx_distinct call and counts itself."""
    try:
        s.set("prefer_approx_distinct", True)
        r = s.sql("SELECT count(DISTINCT l_partkey) FROM lineitem")
        assert r.stats.approx_rewrites == 1
        native = one(s, "SELECT approx_distinct(l_partkey) FROM lineitem")
        assert r.rows[0][0] == native
    finally:
        s.set("prefer_approx_distinct", False)
    r = s.sql("SELECT count(DISTINCT l_partkey) FROM lineitem")
    exact = tpch_sqlite_tiny.execute(
        "SELECT count(DISTINCT l_partkey) FROM lineitem").fetchone()[0]
    assert r.rows[0][0] == exact and r.stats.approx_rewrites == 0
