"""Generator invariants: determinism, split independence, FK validity.
(Reference analog: the airlift-tpch generator's determinism that all of
presto-tests relies on.)"""

import numpy as np
import pytest

from presto_tpu.connectors import tpch


SF = 0.01


def test_row_counts_scale():
    assert tpch.row_count("nation", SF) == 25
    assert tpch.row_count("region", SF) == 5
    assert tpch.row_count("orders", SF) == 15_000
    n = tpch.row_count("lineitem", SF)
    assert 14_000 * 4 * SF * 100 / 100 < n < 7 * 15_000


@pytest.mark.parametrize("table", ["orders", "customer", "part", "supplier", "partsupp"])
def test_split_independence(table):
    whole = tpch.generate(table, SF)
    part = tpch.generate(table, SF, 500, 600)
    for col in whole:
        assert np.array_equal(whole[col][500:600], part[col]), col


def test_lineitem_split_independence():
    whole = tpch.generate("lineitem", SF)
    a0, _ = tpch.lineitem_offsets(500, 600)
    part = tpch.generate("lineitem", SF, 500, 600)
    m = len(part["l_orderkey"])
    for col in whole:
        assert np.array_equal(whole[col][a0:a0 + m], part[col]), col


def test_splits_cover_table():
    ranges = tpch.split_ranges("orders", SF, 7)
    assert ranges[0][0] == 0 and ranges[-1][1] == 15_000
    got = np.concatenate(
        [tpch.generate("orders", SF, a, b)["o_orderkey"] for a, b in ranges]
    )
    assert np.array_equal(got, tpch.generate("orders", SF)["o_orderkey"])


def test_foreign_keys_valid():
    li = tpch.generate("lineitem", SF)
    ps = tpch.generate("partsupp", SF)
    orders = tpch.generate("orders", SF)
    pairs = set(zip(ps["ps_partkey"].tolist(), ps["ps_suppkey"].tolist()))
    lpairs = set(zip(li["l_partkey"].tolist(), li["l_suppkey"].tolist()))
    assert lpairs <= pairs
    assert set(li["l_orderkey"].tolist()) <= set(orders["o_orderkey"].tolist())
    assert orders["o_custkey"].min() >= 1
    assert orders["o_custkey"].max() <= tpch.row_count("customer", SF)
    cust = tpch.generate("customer", SF)
    assert cust["c_nationkey"].max() <= 24


def test_value_domains():
    li = tpch.generate("lineitem", SF)
    assert set(np.unique(li["l_returnflag"])) <= {"A", "N", "R"}
    assert set(np.unique(li["l_linestatus"])) == {"F", "O"}
    assert li["l_discount"].min() >= 0.0 and li["l_discount"].max() <= 0.1
    assert li["l_quantity"].min() >= 1 and li["l_quantity"].max() <= 50
    assert (li["l_shipdate"] > li["l_commitdate"] - 200).all()
    assert (li["l_receiptdate"] > li["l_shipdate"]).all()


def test_sqlite_oracle_loads():
    from tests.sqlite_oracle import build_sqlite

    conn = build_sqlite(SF)
    (n,) = conn.execute("SELECT count(*) FROM lineitem").fetchone()
    assert n == tpch.row_count("lineitem", SF)
    (rev,) = conn.execute(
        "SELECT sum(l_extendedprice * l_discount) FROM lineitem WHERE l_discount > 0.05"
    ).fetchone()
    assert rev > 0
