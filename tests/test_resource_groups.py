"""Resource-group scheduling policies + CPU limits (VERDICT r4
missing #7).

Reference: execution/resourceGroups/InternalResourceGroup.java — FAIR /
WEIGHTED / WEIGHTED_FAIR / QUERY_PRIORITY subgroup scheduling,
softCpuLimit (weight penalty) / hardCpuLimit (admission block) with
quota regeneration, queue limits, selector routing.
"""

import threading

import pytest

from presto_tpu.server.resource_groups import (QueryRejected,
                                               ResourceGroupManager,
                                               _parse_duration_s)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _drain(m, group, n=100):
    """Release n times to let queued tickets through."""
    for _ in range(n):
        m.release(group)


def test_fifo_within_group():
    m = ResourceGroupManager()
    m.add_group("global.g", hard_concurrency_limit=1, max_queued=10)
    m.add_selector("global.g")
    g = m.acquire("u")  # occupies the slot
    order = []
    threads = []

    def worker(i):
        grp = m.acquire("u", timeout=10)
        order.append(i)
        m.release(grp)

    for i in range(3):
        t = threading.Thread(target=worker, args=(i,))
        t.start()
        threads.append(t)
        # deterministic arrival order
        while g._queue and len(g._queue) < i + 1:
            pass
        import time as _t

        _t.sleep(0.02)
    m.release(g)
    for t in threads:
        t.join(timeout=10)
    assert order == [0, 1, 2]


def test_queue_limit_rejects():
    m = ResourceGroupManager()
    grp = m.add_group("global.g", hard_concurrency_limit=1, max_queued=0)
    m.add_selector("global.g")
    m.acquire("u")
    with pytest.raises(QueryRejected):
        m.acquire("u", timeout=0.1)
    assert grp.total_rejected == 1


def test_fair_policy_orders_children_by_arrival():
    m = ResourceGroupManager()
    m.add_group("global.parent", hard_concurrency_limit=1)
    m.add_group("global.parent.a", hard_concurrency_limit=1)
    m.add_group("global.parent.b", hard_concurrency_limit=1)
    m.add_selector("global.parent.a", user="alice")
    m.add_selector("global.parent.b", user="bob")
    first = m.acquire("alice")
    grants = []

    def worker(user):
        g = m.acquire(user, timeout=10)
        grants.append(user)
        # hold until drained externally

    tb = threading.Thread(target=worker, args=("bob",))
    tb.start()
    while not m._resolve("global.parent.b")._queue:
        pass
    ta = threading.Thread(target=worker, args=("alice",))
    ta.start()
    while not m._resolve("global.parent.a")._queue:
        pass
    m.release(first)  # parent slot frees: bob queued FIRST, bob wins
    tb.join(timeout=10)
    ta.join(timeout=2)  # alice still queued (parent limit 1)
    assert grants == ["bob"]
    m.release(m._resolve("global.parent.b"))
    ta.join(timeout=10)
    assert grants == ["bob", "alice"]


def test_weighted_policy_shares_by_weight():
    m = ResourceGroupManager()
    m.add_group("global.p", hard_concurrency_limit=1,
                scheduling_policy="weighted")
    m.add_group("global.p.big", scheduling_weight=3)
    m.add_group("global.p.small", scheduling_weight=1)
    m.add_selector("global.p.big", user="big.*")
    m.add_selector("global.p.small", user="small.*")
    blocker = m.acquire("other")  # root default group? no: selector
    # hold the parent's only slot via the big group
    grants = []
    done = threading.Event()

    def worker(user):
        g = m.acquire(user, timeout=10)
        grants.append(user.rstrip("0123456789"))
        m.release(g)

    m.release(blocker)
    hold = m.acquire("big0")  # occupy the slot so the rest queue
    threads = []
    for i in range(1, 9):
        for u in (f"big{i}", f"small{i}"):
            t = threading.Thread(target=worker, args=(u,))
            t.start()
            threads.append(t)
    # wait until all 16 queued
    p = m._resolve("global.p")
    while p.queued < 16:
        pass
    m.release(hold)
    for t in threads:
        t.join(timeout=20)
    assert len(grants) == 16
    # stride scheduling: in every 4-grant window, ~3 bigs to 1 small
    first8 = grants[:8]
    assert first8.count("big") >= 5
    done.set()


def test_query_priority_policy():
    m = ResourceGroupManager()
    m.add_group("global.q", hard_concurrency_limit=1,
                scheduling_policy="query_priority")
    m.add_group("global.q.leaf", hard_concurrency_limit=1,
                scheduling_policy="query_priority")
    m.add_selector("global.q.leaf")
    hold = m.acquire("u")
    grants = []

    def worker(prio):
        g = m.acquire("u", priority=prio, timeout=10)
        grants.append(prio)
        m.release(g)

    threads = []
    leaf = m._resolve("global.q.leaf")
    for prio in (1, 5, 3):
        t = threading.Thread(target=worker, args=(prio,))
        t.start()
        threads.append(t)
        while len(leaf._queue) < len(threads):
            pass
    m.release(hold)
    for t in threads:
        t.join(timeout=10)
    assert grants == [5, 3, 1]


def test_hard_cpu_limit_blocks_until_regenerated():
    clock = FakeClock()
    m = ResourceGroupManager(now_fn=clock)
    m.add_group("global.cpu", hard_concurrency_limit=10,
                hard_cpu_limit_s=5.0, cpu_quota_generation_per_s=1.0)
    m.add_selector("global.cpu")
    g = m.acquire("u")
    m.release(g, cpu_s=8.0)  # over the 5s hard limit
    with pytest.raises(QueryRejected):
        m.acquire("u", timeout=0.05)
    clock.t += 4.0  # regenerate 4s of quota: usage 8 -> 4 < 5
    g2 = m.acquire("u", timeout=1)
    m.release(g2)


def test_soft_cpu_limit_halves_weight():
    clock = FakeClock()
    m = ResourceGroupManager(now_fn=clock)
    m.add_group("global.p", hard_concurrency_limit=1,
                scheduling_policy="weighted_fair")
    a = m.add_group("global.p.a", scheduling_weight=2,
                    soft_cpu_limit_s=1.0)
    m.add_group("global.p.b", scheduling_weight=2)
    a.cpu_usage_s = 10.0  # way over soft limit
    assert a._effective_weight(clock()) == pytest.approx(1.0)
    assert m._resolve("global.p.b")._effective_weight(clock()) == \
        pytest.approx(2.0)


def test_load_config_policies_and_durations():
    m = ResourceGroupManager()
    m.load_config({
        "groups": [
            {"name": "global.etl", "hardConcurrencyLimit": 2,
             "maxQueued": 5, "schedulingPolicy": "WEIGHTED_FAIR",
             "schedulingWeight": 4, "softCpuLimit": "90s",
             "hardCpuLimit": "2m"},
        ],
        "selectors": [{"user": "etl.*", "group": "global.etl"}],
    })
    g = m._resolve("global.etl")
    assert g.scheduling_policy == "weighted_fair"
    assert g.scheduling_weight == 4
    assert g.soft_cpu_limit_s == 90.0
    assert g.hard_cpu_limit_s == 120.0
    assert m.select_group("etl_nightly").full_name == "global.etl"
    info = {i["name"]: i for i in m.info()}
    assert info["global.etl"]["schedulingPolicy"] == "weighted_fair"


def test_parse_duration():
    assert _parse_duration_s("100ms") == pytest.approx(0.1)
    assert _parse_duration_s("2m") == 120.0
    assert _parse_duration_s(7) == 7.0
    assert _parse_duration_s(None) is None


def test_release_with_cpu_accumulates_up_the_tree():
    # fixed clock: with a real clock the 1/s regeneration would drain
    # usage between release and the assertions
    m = ResourceGroupManager(now_fn=FakeClock())
    m.add_group("global.p.leaf")
    m.add_selector("global.p.leaf")
    g = m.acquire("u")
    m.release(g, cpu_s=2.5)
    assert m._resolve("global.p.leaf").cpu_usage_s == pytest.approx(2.5)
    assert m._resolve("global.p").cpu_usage_s == pytest.approx(2.5)
    assert m.root.cpu_usage_s == pytest.approx(2.5)
