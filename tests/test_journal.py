"""Query-journal unit tests (ISSUE 17): the fleet-visible resumable
state behind journaled in-flight query failover.

Every test runs against a tmp_path root — the journal dir is shared
fleet state, so tests must never touch the default spill-base journal
(coordinator ids like "A"/"B" recur across the suite).  The fault legs
exercise the `journal:WRITE` / `journal:READ` choke points: a journal
fault degrades (journal-less execution, skipped entry), never fails."""

import json
import os
import threading

import pytest

from presto_tpu.parallel import faults as F
from presto_tpu.parallel import journal as J


@pytest.fixture(autouse=True)
def _no_global_faults():
    yield
    F.install(None)


# ---- configuration ----------------------------------------------------


def test_root_dir_precedence():
    assert J.root_dir({"query_journal_path": "/j"}) == "/j"
    assert J.root_dir({"spill_path": "/s"}) == os.path.join("/s", "journal")
    assert J.root_dir({}) == os.path.join(J.DEFAULT_SPILL_BASE, "journal")
    # explicit path wins over the spill base
    assert J.root_dir({"query_journal_path": "/j",
                       "spill_path": "/s"}) == "/j"


def test_enabled_tri_state():
    # auto journals exactly when a fleet exists to adopt the queries
    assert not J.enabled({}, fleet_attached=False)
    assert J.enabled({}, fleet_attached=True)
    assert J.enabled({"query_journal": "auto"}, fleet_attached=True)
    # explicit on/off is respected regardless of the fleet
    for on in (True, "true", "on", "1"):
        assert J.enabled({"query_journal": on}, fleet_attached=False)
    for off in (False, "false", "off", "0", ""):
        assert not J.enabled({"query_journal": off}, fleet_attached=True)


def test_props_fingerprint_stable_and_sensitive():
    a = {"x": 1, "y": "z"}
    assert J.props_fingerprint(a) == J.props_fingerprint({"y": "z", "x": 1})
    assert J.props_fingerprint(a) != J.props_fingerprint({"x": 2, "y": "z"})
    # unserializable values degrade to repr, never raise
    assert J.props_fingerprint({"f": object()})


def test_entry_schema():
    e = J.entry_for("q1", "SELECT 1", "A", {"k": 1}, ddir="/d",
                    layout=["w0", "w1"], attempt=2, binds=[7])
    assert e["queryId"] == "q1" and e["sql"] == "SELECT 1"
    assert e["coord"] == "A" and e["state"] == "RUNNING"
    assert e["ddir"] == "/d" and e["layout"] == ["w0", "w1"]
    assert e["attempt"] == 2 and e["binds"] == [7]
    assert e["completed"] == [] and e["propsFp"]


# ---- write/read/remove round trip -------------------------------------


def test_write_read_remove_roundtrip(tmp_path):
    jr = J.QueryJournal(str(tmp_path), coord_id="A")
    e = J.entry_for("q1", "SELECT 1", "A", {})
    assert jr.write(e)
    # whole-entry tmp+replace: no temp residue next to the entry
    assert sorted(os.listdir(tmp_path)) == [f"q1{J.SUFFIX}"]
    got = jr.read("q1")
    assert got == e
    assert jr.read("missing") is None
    jr.remove("q1")
    assert jr.read("q1") is None
    jr.remove("q1")  # idempotent
    st = jr.stats()
    assert st["writes"] == 1 and st["removed"] == 1
    assert st["write_errors"] == 0 and st["read_errors"] == 0


def test_entries_filters_by_coordinator(tmp_path):
    jr = J.QueryJournal(str(tmp_path), coord_id="A")
    jr.write(J.entry_for("q2", "SELECT 2", "B", {}))
    jr.write(J.entry_for("q1", "SELECT 1", "A", {}))
    jr.write(J.entry_for("q3", "SELECT 3", "A", {}))
    assert [e["queryId"] for e in jr.entries()] == ["q1", "q2", "q3"]
    assert [e["queryId"] for e in jr.entries(coord="A")] == ["q1", "q3"]
    assert [e["queryId"] for e in jr.entries(coord="C")] == []


def test_entry_without_query_id_is_rejected(tmp_path):
    jr = J.QueryJournal(str(tmp_path))
    assert not jr.write({"sql": "SELECT 1"})
    assert jr.stats()["writes"] == 0


def test_concurrent_writes_never_tear(tmp_path):
    jr = J.QueryJournal(str(tmp_path), coord_id="A")

    def hammer(i):
        for n in range(20):
            jr.write(J.entry_for("q-shared", f"SELECT {i}", "A", {},
                                 attempt=n))

    ths = [threading.Thread(target=hammer, args=(i,)) for i in range(4)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    got = jr.read("q-shared")  # any writer's entry, never a torn one
    assert got is not None and got["queryId"] == "q-shared"
    assert jr.stats()["write_errors"] == 0


# ---- fault surface: journal:WRITE / journal:READ ----------------------


def test_write_fault_fails_cleanly(tmp_path):
    jr = J.QueryJournal(str(tmp_path))
    F.install(F.FaultPlan.parse("journal:WRITE:*:1:fail"))
    assert not jr.write(J.entry_for("q1", "SELECT 1", "A", {}))
    assert jr.read("q1") is None  # nothing landed
    assert jr.stats()["write_errors"] == 1
    # the fault was one-shot: the retry persists
    assert jr.write(J.entry_for("q1", "SELECT 1", "A", {}))
    assert jr.read("q1") is not None


def test_write_drop_is_a_silent_loss(tmp_path):
    jr = J.QueryJournal(str(tmp_path))
    F.install(F.FaultPlan.parse("journal:WRITE:*:1:drop"))
    # the caller believes the write persisted — that is the fault
    assert jr.write(J.entry_for("q1", "SELECT 1", "A", {}))
    assert jr.read("q1") is None
    assert jr.stats()["writes"] == 1 and jr.stats()["write_errors"] == 0


@pytest.mark.parametrize("action", ["corrupt", "truncate"])
def test_damaged_write_reads_as_none(tmp_path, action):
    jr = J.QueryJournal(str(tmp_path))
    F.install(F.FaultPlan.parse(f"journal:WRITE:*:1:{action}"))
    jr.write(J.entry_for("q1", "SELECT 1", "A", {}))
    F.install(None)
    # the file exists but is damaged: read reports None and counts it
    assert os.path.exists(jr.path("q1"))
    assert jr.read("q1") is None
    assert jr.stats()["read_errors"] == 1
    # ... and the adopter-facing listing skips it instead of crashing
    assert jr.entries() == []


def test_read_fault_skips_entry(tmp_path):
    jr = J.QueryJournal(str(tmp_path))
    jr.write(J.entry_for("q1", "SELECT 1", "A", {}))
    F.install(F.FaultPlan.parse("journal:READ:*:1:corrupt"))
    assert jr.read("q1") is None
    assert jr.stats()["read_errors"] == 1
    F.install(None)
    assert jr.read("q1") is not None  # the file itself was untouched


def test_hand_damaged_entry_is_skipped(tmp_path):
    """A real torn/garbage file (no fault injection): unreadable entries
    are skipped by entries() so adoption survives a bad journal."""
    jr = J.QueryJournal(str(tmp_path))
    jr.write(J.entry_for("q1", "SELECT 1", "A", {}))
    with open(jr.path("q0"), "w") as f:
        f.write("{not json")
    with open(jr.path("q2"), "w") as f:
        f.write(json.dumps(["not", "a", "dict"]))
    assert [e["queryId"] for e in jr.entries()] == ["q1"]
    assert jr.stats()["read_errors"] == 2
