"""ML functions (the presto-ml module role): learn/classify/regress
validated against known ground truth."""

import numpy as np
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.catalog import Catalog, MemoryTable


@pytest.fixture(scope="module")
def s():
    rng = np.random.default_rng(12)
    n = 2000
    x1 = rng.normal(0, 1, n)
    x2 = rng.normal(0, 1, n)
    # separable-ish classes + linear target with known coefficients
    label = np.where(x1 + 2 * x2 > 0, "pos", "neg")
    y = 3.0 * x1 - 2.0 * x2 + 5.0 + rng.normal(0, 0.01, n)
    cat = Catalog()
    cat.register(MemoryTable(
        "d", {"x1": T.DOUBLE, "x2": T.DOUBLE, "label": T.VARCHAR,
              "y": T.DOUBLE},
        {"x1": x1, "x2": x2,
         "label": np.asarray(label, dtype=object), "y": y}))
    return presto_tpu.connect(cat)


def test_learn_classifier_and_classify(s):
    acc = s.sql(
        "WITH m AS (SELECT learn_classifier(label, features(x1, x2)) "
        "AS model FROM d) "
        "SELECT avg(CASE WHEN classify(features(x1, x2), "
        "(SELECT model FROM m)) = label THEN 1.0 ELSE 0.0 END) "
        "FROM d").rows[0][0]
    assert acc > 0.97


def test_learn_regressor_and_regress(s):
    err = s.sql(
        "WITH m AS (SELECT learn_regressor(y, features(x1, x2)) "
        "AS model FROM d) "
        "SELECT avg(abs(regress(features(x1, x2), "
        "(SELECT model FROM m)) - y)) FROM d").rows[0][0]
    assert err < 0.05


def test_grouped_models(s):
    rows = s.sql(
        "SELECT sign, count(*) FROM ("
        "  SELECT CASE WHEN x1 > 0 THEN 'r' ELSE 'l' END AS sign, "
        "         label, x1, x2 FROM d) t "
        "GROUP BY sign ORDER BY sign").rows
    assert len(rows) == 2  # sanity on the grouping shape itself
    models = s.sql(
        "SELECT sign, learn_regressor(x1, features(x2)) FROM ("
        "  SELECT CASE WHEN x1 > 0 THEN 'r' ELSE 'l' END AS sign, "
        "         x1, x2 FROM d) t GROUP BY sign").rows
    assert len(models) == 2 and all(len(m[1]) > 10 for m in models)


def test_cross_join_model_form(s):
    """Review regression: the canonical presto-ml CROSS JOIN form
    (model as a per-row column) must work."""
    acc = s.sql(
        "SELECT avg(CASE WHEN classify(features(x1, x2), model) = label "
        "THEN 1.0 ELSE 0.0 END) FROM d CROSS JOIN "
        "(SELECT learn_classifier(label, features(x1, x2)) AS model "
        "FROM d) m").rows[0][0]
    assert acc > 0.97


def test_regressor_rejects_varchar_label(s):
    with pytest.raises(Exception):
        s.sql("SELECT learn_regressor(label, features(x1)) FROM d")


def test_null_features_skipped(s):
    """Rows whose features are NULL must not poison training."""
    err = s.sql(
        "WITH t AS (SELECT y, x1, CASE WHEN x2 > 1.5 THEN "
        "CAST(NULL AS DOUBLE) ELSE x2 END AS x2n FROM d), "
        "m AS (SELECT learn_regressor(y, features(x1, x2n)) AS model "
        "FROM t) "
        "SELECT avg(abs(regress(features(x1, x2n), "
        "(SELECT model FROM m)) - y)) FROM t WHERE x2n IS NOT NULL"
    ).rows[0][0]
    assert err < 0.05
