"""Null-aware NOT IN / IN semantics (round-4 ADVICE: MARK joins emitted
a 2-valued mark, and NOT IN lowered to a plain ANTI join with EXISTS
semantics — both kept rows Presto filters).

Reference: SemiJoinNode's semiJoinOutput is NULL when there is no match
but the probe value is NULL or the build side contains NULLs; a
FilterNode over NOT(mark) then drops the row (3-valued logic).
"""

import presto_tpu
from presto_tpu.catalog import Catalog


BASE = "FROM (VALUES (1), (2), (CAST(NULL AS BIGINT))) t(a)"
U_NULL = "(VALUES (2), (CAST(NULL AS BIGINT)))"
U_CLEAN = "(VALUES (2))"


def _s():
    return presto_tpu.connect(Catalog())


def test_not_in_build_side_null_filters_all_nonmatches():
    s = _s()
    r = s.sql(f"SELECT a {BASE} WHERE a NOT IN (SELECT b FROM {U_NULL} u(b))")
    assert r.rows == []


def test_not_in_null_probe_filtered():
    s = _s()
    r = s.sql(f"SELECT a {BASE} WHERE a NOT IN (SELECT b FROM {U_CLEAN} u(b))")
    assert r.rows == [(1,)]


def test_not_in_under_or_uses_null_mark():
    s = _s()
    r = s.sql(f"SELECT a {BASE} WHERE a NOT IN (SELECT b FROM {U_NULL} u(b))"
              " OR a = 2")
    assert r.rows == [(2,)]


def test_in_semantics_unchanged():
    s = _s()
    assert s.sql(
        f"SELECT a {BASE} WHERE a IN (SELECT b FROM {U_NULL} u(b))"
    ).rows == [(2,)]
    assert s.sql(
        f"SELECT a {BASE} WHERE a IN (SELECT b FROM {U_CLEAN} u(b)) OR a = 1"
    ).rows == [(1,), (2,)]


def test_values_cast_null_literal():
    s = _s()
    r = s.sql("SELECT count(*), count(a) FROM "
              "(VALUES (1), (CAST(NULL AS BIGINT))) t(a)")
    assert r.rows == [(2, 1)]


def test_empty_side_outer_joins():
    """Review regression (round 4): RIGHT/FULL with a statically-empty
    probe preserve the build side's rows null-extended; empty build
    sides never crash the gather path."""
    s = _s()
    got = s.sql("SELECT k FROM (SELECT 1 AS x FROM (VALUES (1)) v(q) "
                "LIMIT 0) l RIGHT JOIN (VALUES (1), (2)) u(k) "
                "ON l.x = u.k ORDER BY k").rows
    assert got == [(1,), (2,)]
    got = s.sql("SELECT x, k FROM (SELECT q AS x FROM (VALUES (9)) v(q) "
                "LIMIT 0) l FULL JOIN (VALUES (1)) u(k) "
                "ON l.x = u.k").rows
    assert got == [(None, 1)]
    got = s.sql("SELECT k FROM (VALUES (1), (2)) u(k) LEFT JOIN "
                "(SELECT 5 AS y FROM (VALUES (0)) w(z) LIMIT 0) r "
                "ON u.k = r.y ORDER BY k").rows
    assert got == [(1,), (2,)]


def test_not_in_runtime_empty_build_keeps_all_rows():
    # round-5 ADVICE: a build side that is empty only at RUNTIME (rows
    # exist, all filtered) must behave like the statically-empty case:
    # `x NOT IN (empty)` is TRUE even for NULL x.
    s = _s()
    r = s.sql(f"SELECT a {BASE} WHERE a NOT IN "
              f"(SELECT b FROM {U_NULL} u(b) WHERE b > 100)")
    assert sorted(x[0] is None and -1 or x[0] for x in r.rows) == [-1, 1, 2]


def test_not_in_build_all_null_keys_still_filters():
    # a build of ONLY null keys is NOT empty: the IN-list is {NULL},
    # so every NOT IN is NULL -> filtered.
    s = _s()
    r = s.sql(f"SELECT a {BASE} WHERE a NOT IN "
              "(SELECT b FROM (VALUES (CAST(NULL AS BIGINT))) u(b))")
    assert r.rows == []
