"""SHOW FUNCTIONS/SESSION/CATALOGS/SCHEMAS/STATS, DESCRIBE, and
TABLESAMPLE.

Reference: presto-main ShowQueriesRewrite + ShowStatsRewrite
(SHOW ... rewritten over metadata), SqlBase.g4 sampledRelation +
SampleNode.
"""

import numpy as np
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.catalog import Catalog, MemoryTable


@pytest.fixture(scope="module")
def s():
    cat = Catalog()
    cat.register(MemoryTable(
        "t", {"a": T.BIGINT, "b": T.VARCHAR},
        {"a": np.arange(1000, dtype=np.int64),
         "b": np.asarray([f"s{i % 7}" for i in range(1000)], object)}))
    return presto_tpu.connect(cat)


def test_show_functions(s):
    rows = s.sql("SHOW FUNCTIONS").rows
    byname = dict(rows)
    assert byname["abs"] == "scalar"
    assert byname["sum"] == "aggregate"
    assert byname["row_number"] == "window"
    assert len(rows) > 350


def test_show_session(s):
    rows = dict(s.sql("SHOW SESSION").rows)
    assert "execution_mode" in rows or len(rows) > 5


def test_show_catalogs_and_schemas(s):
    cats = [r[0] for r in s.sql("SHOW CATALOGS").rows]
    assert "memory" in cats
    schemas = [r[0] for r in s.sql("SHOW SCHEMAS").rows]
    assert "default" in schemas


def test_describe(s):
    rows = s.sql("DESCRIBE t").rows
    assert rows == s.sql("DESC t").rows == \
        s.sql("SHOW COLUMNS FROM t").rows
    assert ("a", "BIGINT") in rows


def test_show_stats(s):
    rows = s.sql("SHOW STATS FOR t").rows
    bycol = {r[0]: r for r in rows}
    assert bycol["a"][1] == 1000.0  # ndv
    assert bycol["a"][2] == 0.0 and bycol["a"][3] == 999.0
    assert bycol[None][4] == 1000.0  # row_count summary row


def test_tablesample_bernoulli(s):
    n = s.sql("SELECT count(*) FROM t TABLESAMPLE BERNOULLI (30)"
              ).rows[0][0]
    assert 150 < n < 450  # ~300 expected, loose bounds
    # 100% keeps everything, 0% nothing
    assert s.sql("SELECT count(*) FROM t TABLESAMPLE BERNOULLI (100)"
                 ).rows == [(1000,)]
    assert s.sql("SELECT count(*) FROM t TABLESAMPLE BERNOULLI (0)"
                 ).rows == [(0,)]


def test_tablesample_fresh_across_runs(s):
    q = "SELECT sum(a) FROM t TABLESAMPLE BERNOULLI (50)"
    assert s.sql(q).rows != s.sql(q).rows  # volatile: no stale cache


def test_tablesample_with_alias_and_predicate(s):
    n = s.sql("SELECT count(*) FROM t TABLESAMPLE SYSTEM (100) x "
              "WHERE x.a >= 500").rows[0][0]
    assert n == 500
    n2 = s.sql("SELECT count(*) FROM t AS x TABLESAMPLE BERNOULLI (100)"
               ).rows[0][0]
    assert n2 == 1000


def test_explain_types(s):
    assert s.sql("EXPLAIN (TYPE VALIDATE) SELECT a FROM t").rows == \
        [(True,)]
    txt = s.sql("EXPLAIN (TYPE DISTRIBUTED) "
                "SELECT b, count(*) FROM t GROUP BY b").rows[0][0]
    assert "Fragment" in txt
    assert "PARTIAL" in txt and "FINAL" in txt  # split aggregation
    with pytest.raises(Exception):
        s.sql("EXPLAIN (TYPE VALIDATE) SELECT nope FROM t")


def test_describe_input_output(s):
    s.sql("PREPARE pq FROM SELECT a, b FROM t WHERE a > ? AND b = ?")
    # serving tier infers bound parameter types from the template's
    # column comparisons (reference: DescribeInputRewrite reports the
    # analyzer's parameter types)
    assert s.sql("DESCRIBE INPUT pq").rows == [(0, "bigint"),
                                               (1, "varchar")]
    out = s.sql("DESCRIBE OUTPUT pq").rows
    assert out == [("a", "bigint"), ("b", "varchar")]
