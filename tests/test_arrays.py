"""ARRAY type, array functions, array_agg, UNNEST (reference analogs:
TestArrayFunctions + TestUnnestOperator in presto-main)."""

import numpy as np
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.catalog import Catalog, MemoryTable


@pytest.fixture(scope="module")
def session(tpch_catalog_tiny):
    return presto_tpu.connect(tpch_catalog_tiny)


def test_array_literal_and_functions(session):
    assert session.sql("SELECT ARRAY[3,1,2]").rows == [((3, 1, 2),)]
    r = session.sql(
        "SELECT cardinality(ARRAY[1,2,3]), element_at(ARRAY[10,20], 2), "
        "element_at(ARRAY[10,20], -1), contains(ARRAY[1,2,3], 2), "
        "array_min(ARRAY[5,2,9]), array_max(ARRAY[5,2,9]), "
        "array_position(ARRAY[7,8,9], 9), array_position(ARRAY[7], 99)").rows
    assert r == [(3, 20, 20, True, 2, 9, 3, 0)]
    assert session.sql("SELECT array_sort(ARRAY[3,1,2])").rows == [((1, 2, 3),)]
    assert session.sql(
        "SELECT array_distinct(ARRAY[1,2,1,3,2])").rows == [((1, 2, 3),)]
    assert session.sql(
        "SELECT array_join(ARRAY[1,2,3], '~')").rows == [("1~2~3",)]
    assert session.sql("SELECT slice(ARRAY[1,2,3,4], 2, 2)").rows == [((2, 3),)]


def test_unnest_basic_and_ordinality(session):
    assert session.sql(
        "SELECT x FROM UNNEST(ARRAY[10,20,30]) AS t(x)").rows \
        == [(10,), (20,), (30,)]
    assert session.sql(
        "SELECT x, o FROM UNNEST(ARRAY['a','b']) WITH ORDINALITY AS t(x, o)"
    ).rows == [("a", 1), ("b", 2)]


def test_array_agg_and_lateral_unnest(session):
    r = session.sql(
        "SELECT n_regionkey, array_agg(n_nationkey) AS arr FROM nation "
        "GROUP BY n_regionkey ORDER BY 1").rows
    assert len(r) == 5
    for rk, arr in r:
        expected = {x[0] for x in session.sql(
            f"SELECT n_nationkey FROM nation WHERE n_regionkey = {rk}").rows}
        assert set(arr) == expected
    # round-trip: unnesting the aggregation restores the rows
    flat = session.sql(
        "SELECT q.r, u.x FROM (SELECT n_regionkey AS r, "
        "array_agg(n_nationkey) AS arr FROM nation GROUP BY n_regionkey) AS q "
        "CROSS JOIN UNNEST(q.arr) AS u(x) ORDER BY 2").rows
    base = session.sql(
        "SELECT n_regionkey, n_nationkey FROM nation ORDER BY 2").rows
    assert flat == base


def test_array_agg_strings(session):
    r = session.sql("SELECT array_agg(n_name) FROM nation "
                    "WHERE n_regionkey = 0").rows[0][0]
    assert set(r) == {"ALGERIA", "ETHIOPIA", "KENYA", "MOROCCO", "MOZAMBIQUE"}


def test_unnest_empty_and_errors(session):
    assert session.sql("SELECT x FROM UNNEST(ARRAY[]) AS t(x)").rows == []
    with pytest.raises(Exception, match="ARRAY"):
        session.sql("SELECT x FROM UNNEST(42) AS t(x)")


def test_union_of_arrays_merges_dictionaries(session):
    # regression: codes from different dictionaries must be remapped
    r = session.sql("SELECT ARRAY[1] AS a UNION ALL SELECT ARRAY[2]").rows
    assert sorted(x[0] for x in r) == [(1,), (2,)]


def test_null_elements_and_bounds(session):
    assert session.sql("SELECT ARRAY[1, NULL, 1]").rows == [((1, None, 1),)]
    assert session.sql(
        "SELECT array_distinct(ARRAY[1, NULL, 1])").rows == [((1, None),)]
    # out-of-range element_at is NULL, not an error
    assert session.sql("SELECT element_at(ARRAY[10,20], 5)").rows == [(None,)]
    assert session.sql("SELECT array_min(ARRAY[])").rows == [(None,)]
    assert session.sql(
        "SELECT array_max(ARRAY[NULL, 3, 1])").rows == [(3,)]


def test_array_agg_keeps_nulls(session):
    r = session.sql(
        "SELECT array_agg(CASE WHEN n_nationkey < 3 THEN n_nationkey END) "
        "FROM nation WHERE n_nationkey < 5").rows[0][0]
    assert sorted(x for x in r if x is not None) == [0, 1, 2]
    assert sum(1 for x in r if x is None) == 2


def test_grouping_sets_words_usable_as_identifiers(session):
    assert session.sql("SELECT 1 AS sets, 2 AS grouping").rows == [(1, 2)]


# ---- lambdas / higher-order functions (reference: TestArrayTransform,
# TestArrayFilter, TestArrayReduce, TestZipWith, TestArrayMatch) ----------


def test_lambda_transform_filter(session):
    assert session.sql(
        "SELECT transform(ARRAY[1,2,3], x -> x * 2)").rows == [((2, 4, 6),)]
    assert session.sql(
        "SELECT transform(ARRAY[1,2,NULL], x -> x + 1)").rows \
        == [((2, 3, None),)]
    assert session.sql(
        "SELECT transform(ARRAY['a','bb'], x -> length(x))").rows \
        == [((1, 2),)]
    assert session.sql(
        "SELECT transform(ARRAY[1,2], x -> cast(x AS varchar))").rows \
        == [(("1", "2"),)]
    assert session.sql(
        "SELECT filter(ARRAY[1,2,3,4], x -> x % 2 = 0)").rows == [((2, 4),)]
    # filter drops elements whose predicate is NULL
    assert session.sql(
        "SELECT filter(ARRAY[1,NULL,3], x -> x > 1)").rows == [((3,),)]


def test_lambda_match(session):
    r = session.sql(
        "SELECT any_match(ARRAY[1,2,3], x -> x > 2), "
        "all_match(ARRAY[1,2,3], x -> x > 0), "
        "none_match(ARRAY[1,2,3], x -> x > 5)").rows
    assert r == [(True, True, True)]
    # NULL three-valued logic: no definite match but a NULL candidate
    assert session.sql(
        "SELECT any_match(ARRAY[1,NULL], x -> x > 5)").rows == [(None,)]
    assert session.sql(
        "SELECT any_match(ARRAY[], x -> x > 5)").rows == [(False,)]


def test_lambda_reduce_zip_with(session):
    assert session.sql(
        "SELECT reduce(ARRAY[1,2,3,4], 0, (s, x) -> s + x, s -> s)"
    ).rows == [(10,)]
    assert session.sql(  # 3-arg form defaults to identity output
        "SELECT reduce(ARRAY[1,2,3], 100, (s, x) -> s + x)").rows == [(106,)]
    assert session.sql(
        "SELECT reduce(ARRAY[5, 20, 50], 0.0, (s, x) -> s + x, s -> s / 3)"
    ).rows == [(25.0,)]
    assert session.sql(
        "SELECT zip_with(ARRAY[1,2,3], ARRAY[10,20,30], (x, y) -> x + y)"
    ).rows == [((11, 22, 33),)]
    # shorter side padded with NULL
    assert session.sql(
        "SELECT zip_with(ARRAY[1,2], ARRAY[10,20,30], "
        "(x, y) -> coalesce(x, 0) + y)").rows == [((11, 22, 30),)]


def test_lambda_capture_rejected(session):
    # captures of row columns are rejected (lambda factoring is per
    # distinct array value), surfaced as an execution error
    with pytest.raises(Exception, match="captures"):
        session.sql(
            "SELECT transform(ks, x -> x + k) FROM ("
            "SELECT 1 AS k, ARRAY[1,2] AS ks)")


def test_lambda_transform_on_aggregated_arrays(session):
    r = session.sql(
        "SELECT k, transform(a, x -> x * 10) FROM ("
        "SELECT o_orderstatus AS k, array_agg(o_orderkey) AS a "
        "FROM orders GROUP BY o_orderstatus) ORDER BY k").rows
    base = session.sql(
        "SELECT o_orderstatus, array_agg(o_orderkey) FROM orders "
        "GROUP BY o_orderstatus ORDER BY o_orderstatus").rows
    assert len(r) == len(base)
    for (k1, scaled), (k2, orig) in zip(r, base):
        assert k1 == k2 and scaled == tuple(x * 10 for x in orig)


def test_array_set_functions(session):
    assert session.sql(
        "SELECT flatten(ARRAY[ARRAY[1,2], ARRAY[3]])").rows == [((1, 2, 3),)]
    assert session.sql(
        "SELECT array_remove(ARRAY[1,2,1,3], 1)").rows == [((2, 3),)]
    r = session.sql(
        "SELECT array_union(ARRAY[1,2], ARRAY[2,3]), "
        "array_intersect(ARRAY[1,2,3], ARRAY[2,3,4]), "
        "array_except(ARRAY[1,2,3], ARRAY[2]), "
        "arrays_overlap(ARRAY[1,2], ARRAY[2,3])").rows
    assert r == [((1, 2, 3), (2, 3), (1, 3), True)]
    assert session.sql(
        "SELECT sequence(1, 5), sequence(5, 1, -2)").rows \
        == [((1, 2, 3, 4, 5), (5, 3, 1))]
    assert session.sql(
        "SELECT split('a,b,c', ','), split('a,b,c', ',', 2)").rows \
        == [(("a", "b", "c"), ("a", "b,c"))]
    assert session.sql(
        "SELECT ARRAY[1,2] || ARRAY[3]").rows == [((1, 2, 3),)]
    assert session.sql(
        "SELECT ARRAY[ARRAY[1,2], ARRAY[3]]").rows == [(((1, 2), (3,)),)]


def test_values_with_collection_constants(session):
    """VALUES accepts constant expressions, not just literals
    (reference: VALUES rows are arbitrary constant expressions)."""
    r = session.sql("SELECT set_union(a) FROM (VALUES (ARRAY[1,2]), "
                    "(ARRAY[2,3])) AS t(a)").rows
    assert r == [((1, 2, 3),)]
    r = session.sql("SELECT cardinality(a) FROM (VALUES (ARRAY[1,2,3]),"
                    " (ARRAY[])) AS t(a) ORDER BY 1").rows
    assert r == [(0,), (3,)]
    r = session.sql("SELECT x FROM (VALUES (1+1), (2*3)) AS t(x) "
                    "ORDER BY x").rows
    assert r == [(2,), (6,)]
    r = session.sql("SELECT m['a'] FROM (VALUES (MAP(ARRAY['a'], "
                    "ARRAY[7]))) AS t(m)").rows
    assert r == [(7,)]


def test_collection_order_by_and_min_max_semantic(session):
    """Regression: dictionary canonical order was repr-based, so
    ORDER BY / min / max over ARRAY columns followed string order
    (ARRAY[10] sorted before ARRAY[2])."""
    r = session.sql("SELECT a FROM (VALUES (ARRAY[2]), (ARRAY[10]), "
                    "(ARRAY[1,5])) AS t(a) ORDER BY a").rows
    assert [x[0] for x in r] == [(1, 5), (2,), (10,)]
    r = session.sql("SELECT max(a), min(a) FROM (VALUES (ARRAY[2]), "
                    "(ARRAY[10])) AS t(a)").rows
    assert r == [((10,), (2,))]
