"""The driver's own checks, run in the default suite (round-3 VERDICT
item 10: dryrun/bench failures must be impossible to ship silently —
the suite goes red whenever the driver's checks would).

The driver compile-checks entry() single-chip and runs
dryrun_multichip(N) on an N-virtual-device CPU mesh; both live in
__graft_entry__.py.  conftest.py already pins an 8-device CPU mesh.
"""

import os
import sys

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_entry_compiles_and_runs():
    import __graft_entry__ as G

    fn, args = G.entry()
    out = jax.jit(fn)(*args)
    assert out[0].shape[0] == 8  # 8 Q1 groups


def test_dryrun_multichip_8():
    import __graft_entry__ as G

    G.dryrun_multichip(8)
