"""Differential tests for the round-4 function-breadth batch
(presto_tpu/functions/scalar_ext.py + the new aggregates): every family
checked against an independent python reference computed in the test.

Reference parity targets: operator/scalar/{MathFunctions, StringFunctions,
RegexpFunctions, VarbinaryFunctions, HmacFunctions, UrlFunctions,
DateTimeFunctions, TeradataDateFunctions}, operator/aggregation/
{Corr,Covar,Regr}*, CentralMomentsAggregation, Histogram,
BitwiseAndAggregation, MapUnionAggregation.
"""

import base64
import hashlib
import hmac
import math
import struct
import zlib

import numpy as np
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.catalog import Catalog, MemoryTable


@pytest.fixture(scope="module")
def s():
    cat = Catalog()
    rng = np.random.default_rng(11)
    n = 500
    cat.register(MemoryTable(
        "vals", {"g": T.BIGINT, "x": T.DOUBLE, "y": T.DOUBLE,
                 "i": T.BIGINT, "c": T.BIGINT},
        {"g": rng.integers(0, 4, n),
         "x": rng.normal(3.0, 2.0, n),
         "y": rng.normal(-1.0, 1.5, n),
         "i": rng.integers(-1000, 1000, n),
         "c": rng.integers(1, 50, n)}))
    return presto_tpu.connect(cat)


def one(s, sql):
    return s.sql(sql).rows[0][0]


def close(a, b, tol=1e-9):
    return a == pytest.approx(b, rel=tol, abs=tol)


# ---------------------------------------------------------------------------
# math
# ---------------------------------------------------------------------------


def test_trig_and_conversions(s):
    assert close(one(s, "SELECT sin(0.7)"), math.sin(0.7))
    assert close(one(s, "SELECT atan(1.0)"), math.pi / 4)
    assert close(one(s, "SELECT tanh(0.3)"), math.tanh(0.3))
    assert close(one(s, "SELECT cbrt(27.0)"), 3.0)
    assert close(one(s, "SELECT degrees(pi())"), 180.0)
    assert close(one(s, "SELECT radians(180.0)"), math.pi)
    assert close(one(s, "SELECT log2(8.0)"), 3.0)


def test_mod_matches_java_semantics(s):
    # Java % truncates toward zero (Presto mod): sign follows dividend
    for a, b in ((10, 3), (-10, 3), (10, -3), (-10, -3)):
        want = a - int(a / b) * b
        assert one(s, f"SELECT mod({a}, {b})") == want
    assert close(one(s, "SELECT mod(10.5, 3.0)"), math.fmod(10.5, 3.0))


def test_float_predicates_and_consts(s):
    assert one(s, "SELECT is_nan(nan())") is True
    assert one(s, "SELECT is_finite(1.0)") is True
    assert one(s, "SELECT is_infinite(infinity())") is True


def test_bit_count_and_shifts(s):
    assert one(s, "SELECT bit_count(7, 64)") == 3
    assert one(s, "SELECT bit_count(-1, 64)") == 64
    assert one(s, "SELECT bitwise_logical_shift_right(-1, 60)") == 15
    assert one(s, "SELECT bitwise_arithmetic_shift_right(-16, 2)") == -4


def test_probability_cdfs(s):
    assert close(one(s, "SELECT normal_cdf(0, 1, 1.96)"), 0.9750021048517795,
                 1e-6)
    assert close(one(s, "SELECT inverse_normal_cdf(0, 1, 0.975)"),
                 1.959963984540054, 1e-6)
    assert close(one(s, "SELECT cauchy_cdf(0, 1, 0)"), 0.5)
    assert close(one(s, "SELECT logistic_cdf(0, 1, 0)"), 0.5)
    assert close(one(s, "SELECT laplace_cdf(0, 1, 0)"), 0.5)
    assert close(one(s, "SELECT weibull_cdf(1, 1, 1)"), 1 - math.exp(-1))
    # chi2(k=2) cdf at x: 1 - exp(-x/2)
    assert close(one(s, "SELECT chi_squared_cdf(2, 3.0)"),
                 1 - math.exp(-1.5), 1e-6)
    assert close(one(s, "SELECT beta_cdf(1, 1, 0.3)"), 0.3, 1e-6)


def test_base_conversion(s):
    assert one(s, "SELECT to_base(255, 16)") == "ff"
    assert one(s, "SELECT to_base(-10, 2)") == "-1010"
    assert one(s, "SELECT from_base('ff', 16)") == 255
    assert one(s, "SELECT from_base('-1010', 2)") == -10


# ---------------------------------------------------------------------------
# strings / regex
# ---------------------------------------------------------------------------


def test_string_distances(s):
    assert one(s, "SELECT levenshtein_distance('kitten', 'sitting')") == 3
    assert one(s, "SELECT hamming_distance('karolin', 'kathrin')") == 3
    assert close(one(s, "SELECT jaccard_index('abc', 'bcd')"), 2 / 4)


def test_translate_normalize_soundex(s):
    assert one(s, "SELECT translate('abcd', 'bd', 'x')") == "axc"
    assert one(s, "SELECT soundex('Robert')") == "R163"
    assert one(s, "SELECT normalize('Amélie')") == "Amélie"


def test_concat_ws(s):
    assert one(s, "SELECT concat_ws('-', 'a', 'b', 'c')") == "a-b-c"


def test_regexp_long_tail(s):
    assert one(s, "SELECT regexp_count('1a2b3c', '[0-9]')") == 3
    assert one(s, "SELECT regexp_position('abc123', '[0-9]')") == 4
    assert one(s, "SELECT regexp_extract_all('1a2b3', '[0-9]')") == \
        ("1", "2", "3")
    assert one(s, "SELECT regexp_split('a1b22c', '[0-9]+')") == \
        ("a", "b", "c")


# ---------------------------------------------------------------------------
# binary / hashing
# ---------------------------------------------------------------------------


def test_codec_roundtrips(s):
    assert one(s, "SELECT to_hex(to_utf8('ab'))") == "6162"
    assert one(s, "SELECT from_utf8(from_hex('6162'))") == "ab"
    assert one(s, "SELECT to_base64(to_utf8('presto'))") == \
        base64.b64encode(b"presto").decode()
    assert one(s, "SELECT from_utf8(from_base64('cHJlc3Rv'))") == "presto"


def test_hashes(s):
    assert one(s, "SELECT crc32(to_utf8('presto'))") == zlib.crc32(b"presto")
    assert one(s, "SELECT md5(to_utf8('abc'))") == hashlib.md5(b"abc").digest()
    assert one(s, "SELECT sha256(to_utf8('abc'))") == \
        hashlib.sha256(b"abc").digest()
    assert one(s, "SELECT hmac_sha256(to_utf8('msg'), to_utf8('key'))") == \
        hmac.new(b"key", b"msg", "sha256").digest()
    # xxhash64 known-answer (xxhsum of empty input, seed 0)
    assert one(s, "SELECT to_hex(xxhash64(to_utf8('')))") == \
        "EF46DB3751D8E999"


def test_big_endian_and_ieee754(s):
    assert one(s, "SELECT to_big_endian_64(258)") == struct.pack(">q", 258)
    assert one(s, "SELECT from_big_endian_64(to_big_endian_64(-7))") == -7
    assert one(s, "SELECT from_ieee754_64(to_ieee754_64(2.5))") == 2.5


# ---------------------------------------------------------------------------
# URL
# ---------------------------------------------------------------------------


def test_url_functions(s):
    u = "'https://user@example.com:8443/a/b?x=1&y=2#frag'"
    assert one(s, f"SELECT url_extract_protocol({u})") == "https"
    assert one(s, f"SELECT url_extract_host({u})") == "example.com"
    assert one(s, f"SELECT url_extract_port({u})") == 8443
    assert one(s, f"SELECT url_extract_path({u})") == "/a/b"
    assert one(s, f"SELECT url_extract_query({u})") == "x=1&y=2"
    assert one(s, f"SELECT url_extract_fragment({u})") == "frag"
    assert one(s, f"SELECT url_extract_parameter({u}, 'y')") == "2"
    assert one(s, "SELECT url_encode('a b&c')") == "a+b%26c"
    assert one(s, "SELECT url_decode('a+b%26c')") == "a b&c"


# ---------------------------------------------------------------------------
# datetime
# ---------------------------------------------------------------------------


def test_time_fields(s):
    ts = "TIMESTAMP '2026-07-31 13:45:12'"
    assert one(s, f"SELECT hour({ts})") == 13
    assert one(s, f"SELECT minute({ts})") == 45
    assert one(s, f"SELECT second({ts})") == 12
    assert one(s, "SELECT timezone_hour(TIMESTAMP '2026-01-01 00:00:00')") \
        == 0


def test_date_fields_iso(s):
    # 2026-07-31 is a Friday: ISO day_of_week = 5
    assert one(s, "SELECT day_of_week(DATE '2026-07-31')") == 5
    assert one(s, "SELECT day_of_month(DATE '2026-07-31')") == 31
    assert one(s, "SELECT day_of_year(DATE '2026-02-01')") == 32
    # ISO week edge: 2016-01-01 (Friday) belongs to week 53 of 2015
    assert one(s, "SELECT week_of_year(DATE '2016-01-01')") == 53
    assert one(s, "SELECT year_of_week(DATE '2016-01-01')") == 2015
    assert one(s, "SELECT yow(DATE '2026-07-31')") == 2026


def test_formatting_and_parsing(s):
    assert one(s, "SELECT date_format(TIMESTAMP '2026-07-31 09:05:00', "
                  "'%Y-%m-%d %H:%i')") == "2026-07-31 09:05"
    assert one(s, "SELECT format_datetime(DATE '2026-07-31', "
                  "'yyyy/MM/dd')") == "2026/07/31"
    assert one(s, "SELECT date_parse('2026-07-31', '%Y-%m-%d')") is not None
    assert one(s, "SELECT day(date_parse('31/07/2026', '%d/%m/%Y'))") == 31
    assert one(s, "SELECT from_iso8601_date('2026-07-31') = "
                  "DATE '2026-07-31'") is True
    assert one(s, "SELECT to_iso8601(DATE '2026-07-31')") == "2026-07-31"
    assert one(s, "SELECT day(to_date('2026-07-31', 'yyyy-MM-dd'))") == 31


def test_parse_duration(s):
    assert one(s, "SELECT to_milliseconds(parse_duration('1.5s'))") == 1500
    assert one(s, "SELECT to_milliseconds(parse_duration('42ms'))") == 42


# ---------------------------------------------------------------------------
# json / arrays / misc
# ---------------------------------------------------------------------------


def test_json_long_tail(s):
    assert one(s, "SELECT json_array_get('[1, 2, 3]', 1)") == "2"
    assert one(s, "SELECT json_array_get('[1, 2, 3]', -1)") == "3"
    assert one(s, "SELECT json_array_contains('[1, 2, 3]', 2)") is True
    assert one(s, "SELECT json_array_contains('[1, 2]', 5)") is False


def test_array_long_tail(s):
    assert one(s, "SELECT array_sum(ARRAY[1, 2, 3])") == 6
    assert close(one(s, "SELECT array_average(ARRAY[1.0, 2.0, 4.0])"),
                 7.0 / 3)
    assert one(s, "SELECT array_duplicates(ARRAY[1, 2, 1, 3, 3])") == (1, 3)
    assert one(s, "SELECT array_has_duplicates(ARRAY[1, 2, 1])") is True


def test_typeof(s):
    assert one(s, "SELECT typeof(1.0)") == "DOUBLE"
    assert one(s, "SELECT typeof('x')") == "VARCHAR"


# ---------------------------------------------------------------------------
# new aggregates, differentially vs numpy
# ---------------------------------------------------------------------------


def _cols(s):
    t = s.catalog.get("vals")
    return t.data


def test_corr_covar_regr(s):
    d = _cols(s)
    x, y = d["x"], d["y"]
    got = s.sql("SELECT corr(y, x), covar_samp(y, x), covar_pop(y, x), "
                "regr_slope(y, x), regr_intercept(y, x) FROM vals").rows[0]
    n = len(x)
    covp = np.mean(x * y) - x.mean() * y.mean()
    assert close(got[0], float(np.corrcoef(x, y)[0, 1]), 1e-6)
    assert close(got[1], float(covp * n / (n - 1)), 1e-6)
    assert close(got[2], float(covp), 1e-6)
    slope = covp / x.var()
    assert close(got[3], float(slope), 1e-6)
    assert close(got[4], float(y.mean() - slope * x.mean()), 1e-6)


def test_skewness_kurtosis(s):
    d = _cols(s)
    x = d["x"]
    n = len(x)
    mu = x.mean()
    sd = x.std(ddof=1)
    skew = n / ((n - 1) * (n - 2)) * np.sum(((x - mu) / sd) ** 3)
    kurt = (n * (n + 1) / ((n - 1) * (n - 2) * (n - 3))
            * np.sum(((x - mu) / sd) ** 4)
            - 3 * (n - 1) ** 2 / ((n - 2) * (n - 3)))
    got = s.sql("SELECT skewness(x), kurtosis(x) FROM vals").rows[0]
    assert close(got[0], float(skew), 1e-5)
    assert close(got[1], float(kurt), 1e-5)


def test_entropy(s):
    d = _cols(s)
    c = d["c"].astype(float)
    S = c.sum()
    want = math.log2(S) - float(np.sum(c * np.log2(c))) / S
    assert close(one(s, "SELECT entropy(c) FROM vals"), want, 1e-6)


def test_bitwise_aggs(s):
    d = _cols(s)
    want_and = int(np.bitwise_and.reduce(d["i"]))
    want_or = int(np.bitwise_or.reduce(d["i"]))
    got = s.sql("SELECT bitwise_and_agg(i), bitwise_or_agg(i) "
                "FROM vals").rows[0]
    assert got == (want_and, want_or)


def test_grouped_new_aggs_match_numpy(s):
    d = _cols(s)
    rows = s.sql("SELECT g, corr(y, x), skewness(x) FROM vals "
                 "GROUP BY g ORDER BY g").rows
    for g, corr_g, skew_g in rows:
        m = d["g"] == g
        x, y = d["x"][m], d["y"][m]
        assert close(corr_g, float(np.corrcoef(x, y)[0, 1]), 1e-6)


def test_histogram(s):
    got = one(s, "SELECT histogram(v) FROM (VALUES ('a'), ('b'), ('a'), "
                 "('a')) t(v)")
    assert dict(got) == {"a": 3, "b": 1}


def test_numeric_histogram(s):
    got = one(s, "SELECT numeric_histogram(2, v) FROM "
                 "(VALUES (1.0), (2.0), (10.0), (11.0)) t(v)")
    assert dict(got) == {1.5: 2.0, 10.5: 2.0}


def test_map_union(s):
    got = one(s, "SELECT map_union(m) FROM "
                 "(SELECT map(ARRAY['a'], ARRAY[1]) AS m "
                 "UNION ALL SELECT map(ARRAY['b'], ARRAY[2])) t")
    assert dict(got) == {"a": 1, "b": 2}
