"""Parquet read/write path (storage/parquet.py + connectors/parquet.py)
validated against an INDEPENDENT implementation: pyarrow writes the
files our decoder reads (every codec/encoding combination), and pyarrow
reads back the files our encoder writes.

Reference parity targets: presto-parquet readers + writer, the hive
connector's parquet page source."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import presto_tpu
from presto_tpu import types as T
from presto_tpu.catalog import Catalog
from presto_tpu.storage.parquet import (ParquetFile, snappy_decompress,
                                        write_parquet)


@pytest.fixture()
def rich_table():
    rng = np.random.default_rng(5)
    n = 5000
    return pa.table({
        "i32": pa.array(rng.integers(-100, 100, n), pa.int32()),
        "i64": pa.array(rng.integers(-10**12, 10**12, n), pa.int64()),
        "f32": pa.array(rng.normal(size=n).astype(np.float32)),
        "f64": pa.array(rng.normal(size=n)),
        "b": pa.array(rng.integers(0, 2, n).astype(bool)),
        "s": pa.array([f"v{int(x)}" for x in rng.integers(0, 50, n)]),
        "d": pa.array(rng.integers(0, 20000, n).astype(np.int32),
                      pa.date32()),
        "opt": pa.array([None if x % 7 == 0 else int(x)
                         for x in range(n)], pa.int64()),
    })


def _assert_matches(path, table):
    ours = ParquetFile(path)
    want = table.to_pydict()
    assert ours.num_rows == table.num_rows
    by_name = {c.name: c for c in ours.columns}
    for name in table.column_names:
        col = by_name[name]
        allv = []
        allok = []
        for gi in range(len(ours.row_groups)):
            vals, valid, _t = ours.read_column(gi, col)
            allv.extend(vals.tolist())
            allok.extend(valid.tolist() if valid is not None
                         else [True] * len(vals))
        for got, ok, exp in zip(allv, allok, want[name]):
            if exp is None:
                assert not ok, (name, got, exp)
            else:
                assert ok, (name, exp)
                if isinstance(exp, float):
                    assert got == pytest.approx(exp, rel=1e-6)
                elif hasattr(exp, "toordinal"):  # date32 -> engine days
                    assert got == exp.toordinal() - 719163
                else:
                    assert got == exp, (name, got, exp)


@pytest.mark.parametrize("codec", ["none", "snappy", "gzip", "zstd"])
@pytest.mark.parametrize("dictionary", [True, False])
def test_read_pyarrow_files(tmp_path, rich_table, codec, dictionary):
    if codec == "zstd":
        pytest.importorskip("zstandard")  # optional codec dep -> skip
    p = str(tmp_path / f"t_{codec}_{dictionary}.parquet")
    pq.write_table(rich_table, p, compression=codec,
                   use_dictionary=dictionary, row_group_size=1500)
    _assert_matches(p, rich_table)


def test_read_data_page_v2(tmp_path, rich_table):
    pytest.importorskip("zstandard")  # file written with zstd below
    p = str(tmp_path / "v2.parquet")
    pq.write_table(rich_table, p, compression="zstd",
                   data_page_version="2.0", row_group_size=2000)
    _assert_matches(p, rich_table)


def test_snappy_decompress_roundtrip():
    # snappy golden vectors: literals + every copy-tag width via a
    # repetitive buffer that compresses with overlapping copies
    try:
        import pyarrow as _pa

        comp = _pa.compress(b"ab" * 400 + b"unique-tail", codec="snappy",
                            asbytes=True)
        assert snappy_decompress(comp) == b"ab" * 400 + b"unique-tail"
    except (ImportError, AttributeError):
        pytest.skip("no snappy compressor available to test against")


def test_our_writer_read_by_pyarrow(tmp_path):
    p = str(tmp_path / "ours.parquet")
    arrays = {
        "a": np.arange(100, dtype=np.int64),
        "s": np.asarray([f"s{i % 9}" for i in range(100)], dtype=object),
        "f": np.ma.masked_array(np.arange(100) * 0.5,
                                np.arange(100) % 5 == 0),
        "flag": np.arange(100) % 3 == 0,
    }
    schema = {"a": T.BIGINT, "s": T.VARCHAR, "f": T.DOUBLE,
              "flag": T.BOOLEAN}
    write_parquet(p, arrays, schema)
    t = pq.read_table(p)  # the independent reader
    assert t.column("a").to_pylist() == list(range(100))
    assert t.column("s").to_pylist() == [f"s{i % 9}" for i in range(100)]
    got_f = t.column("f").to_pylist()
    for i, v in enumerate(got_f):
        if i % 5 == 0:
            assert v is None
        else:
            assert v == i * 0.5
    assert t.column("flag").to_pylist() == [i % 3 == 0
                                            for i in range(100)]


def test_parquet_connector_sql(tmp_path, rich_table):
    pytest.importorskip("zstandard")  # file written with zstd below
    p = str(tmp_path / "t.parquet")
    pq.write_table(rich_table, p, compression="zstd", row_group_size=1000)
    cat = Catalog()
    cat.register_parquet("pq_t", p)
    s = presto_tpu.connect(cat)
    want = rich_table.to_pydict()
    n = s.sql("SELECT count(*) FROM pq_t").rows[0][0]
    assert n == rich_table.num_rows
    total = s.sql("SELECT sum(i64), count(opt) FROM pq_t").rows[0]
    assert total[0] == sum(want["i64"])
    assert total[1] == sum(1 for v in want["opt"] if v is not None)
    top = s.sql("SELECT s, count(*) c FROM pq_t GROUP BY s "
                "ORDER BY c DESC, s LIMIT 3").rows
    import collections

    cnt = collections.Counter(want["s"])
    expect = sorted(cnt.items(), key=lambda kv: (-kv[1], kv[0]))[:3]
    assert [(r[0], r[1]) for r in top] == expect


def test_parquet_ctas_and_insert(tmp_path):
    cat = Catalog()
    s = presto_tpu.connect(cat)
    s.set("localfile_root", str(tmp_path))
    s.sql("CREATE TABLE pt WITH (connector = 'parquet') AS "
          "SELECT a, a * 2 AS b FROM (VALUES (1), (2), (3)) t(a)")
    assert s.sql("SELECT sum(b) FROM pt").rows == [(12,)]
    s.sql("INSERT INTO pt SELECT a, a * 2 FROM (VALUES (10)) t(a)")
    assert s.sql("SELECT count(*), sum(b) FROM pt").rows == [(4, 32)]
    # files readable by the independent implementation
    files = [f for f in os.listdir(tmp_path / "pt")
             if f.endswith(".parquet")]
    assert len(files) == 2
    back = pq.read_table(str(tmp_path / "pt"))
    assert sorted(back.column("a").to_pylist()) == [1, 2, 3, 10]


def test_parquet_splits_align_to_row_groups(tmp_path, rich_table):
    p = str(tmp_path / "t.parquet")
    pq.write_table(rich_table, p, row_group_size=1000)
    from presto_tpu.connectors.parquet import ParquetTable

    t = ParquetTable("t", p)
    splits = t.splits(4)
    assert sum(b - a for a, b in splits) == rich_table.num_rows
    for a, b in splits:
        assert a % 1000 == 0  # snapped to row-group boundaries
    # split reads reassemble exactly
    got = np.concatenate([t.read(["i64"], sp)["i64"] for sp in splits])
    assert got.tolist() == rich_table.to_pydict()["i64"]


def test_read_data_page_v2_no_dictionary(tmp_path, rich_table):
    """v2 pages without dictionaries use the DELTA encodings
    (DELTA_BINARY_PACKED ints, DELTA_BYTE_ARRAY strings)."""
    p = str(tmp_path / "v2nd.parquet")
    pq.write_table(rich_table, p, use_dictionary=False,
                   data_page_version="2.0", row_group_size=2000)
    _assert_matches(p, rich_table)


def test_read_forced_delta_encodings(tmp_path):
    """DELTA_BINARY_PACKED / DELTA_LENGTH_BYTE_ARRAY / DELTA_BYTE_ARRAY
    forced explicitly (pyarrow only emits them on request)."""
    n = 4000
    rng = np.random.default_rng(9)
    tbl = pa.table({
        "i": pa.array(rng.integers(-10**9, 10**9, n), pa.int64()),
        "j": pa.array(np.cumsum(rng.integers(0, 5, n)), pa.int32()),
        "s": pa.array([f"prefix_{i // 10}_{i}" for i in range(n)]),
    })
    for enc in ("DELTA_BINARY_PACKED", "DELTA_LENGTH_BYTE_ARRAY",
                "DELTA_BYTE_ARRAY"):
        p = str(tmp_path / f"{enc}.parquet")
        col_enc = {"i": "DELTA_BINARY_PACKED",
                   "j": "DELTA_BINARY_PACKED", "s": enc} \
            if enc != "DELTA_BINARY_PACKED" else enc
        try:
            pq.write_table(tbl, p, use_dictionary=False,
                           column_encoding=col_enc,
                           data_page_version="2.0")
        except Exception:
            continue  # encoding not writable by this pyarrow build
        _assert_matches(p, tbl)
