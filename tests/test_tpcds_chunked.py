"""Chunked (grouped) execution over TPC-DS fact tables: stream
store_sales/store_returns and catalog_sales/catalog_returns
chunk-by-chunk through the connector-bucketing SPI
(connectors/tpcds_device.py) and match whole-table results.

Reference: grouped execution over connector bucketing
(Lifespan.java:26-38, BucketNodeMap, Connector.java:74); q64 is
BASELINE config 4's query."""

import pytest

import presto_tpu
from presto_tpu.catalog import tpcds_catalog

from tpcds_queries import QUERIES

SF = 0.02


@pytest.fixture(scope="module")
def sessions():
    chunked = presto_tpu.connect(
        tpcds_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    chunked.properties["chunked_rows_threshold"] = 20_000
    chunked.properties["chunk_fact_rows"] = 20_000  # ~3 chunks
    whole = presto_tpu.connect(
        tpcds_catalog(SF, cache_dir="/tmp/presto_tpu_cache"))
    return chunked, whole


def norm(rows):
    return [tuple(round(v, 2) if isinstance(v, float) else v for v in r)
            for r in rows]


# queries covering: store channel star joins (3, 13), store+catalog+
# returns multi-channel (25, 29), catalog-only (15), and the q64
# two-channel self-join — BASELINE config 4's query
@pytest.mark.parametrize("qid", [3, 13, 15, 25, 29, 64])
def test_chunked_matches_whole(sessions, qid):
    chunked, whole = sessions
    got = chunked.sql(QUERIES[qid])
    want = whole.sql(QUERIES[qid])
    assert norm(got.rows) == norm(want.rows)


def test_chunked_mode_actually_used_q64(sessions):
    """q64 must take the chunk-loop path, not fall back: both channels'
    fact tables stream, so the bucketing SPI, the colocated
    sales<->returns joins, and the buffered cs_ui exchange are all
    exercised."""
    chunked, _ = sessions
    from presto_tpu.exec import chunked as CH
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.sql.parser import parse

    stmt = parse(QUERIES[64])
    plan = plan_statement(chunked, stmt)
    assert CH.chunk_plan_needed(chunked, plan)
    r = CH.run_chunked(chunked, stmt, QUERIES[64])
    assert r.rows is not None


def test_chunked_mode_actually_used_store(sessions):
    chunked, _ = sessions
    from presto_tpu.exec import chunked as CH
    from presto_tpu.exec.executor import plan_statement
    from presto_tpu.sql.parser import parse

    stmt = parse(QUERIES[3])
    plan = plan_statement(chunked, stmt)
    assert CH.chunk_plan_needed(chunked, plan)
    r = CH.run_chunked(chunked, stmt, QUERIES[3])
    assert len(r.rows) > 0
