"""Incremental materialized views (ISSUE 20): manifest-delta refresh,
sketch-state rollups, MV-routed serving.

The load-bearing guarantees:
  * refresh-MERGED results are bit-identical to a full recompute — for
    exact aggregates AND sketch estimates — across execution modes,
    dtypes, null masks, empty deltas, and all-null deltas;
  * a fault injected mid-merge leaves the PRIOR snapshot serving;
  * an MV reader mid-poll across TWO consecutive refreshes still
    resolves a complete file list (backing retire_depth=2);
  * delta refresh cost scales with the delta, not the history
    (mv_delta_splits << mv_source_splits);
  * non-append sources degrade LOUDLY to full recompute — counted,
    never wrong.
"""

import glob
import os

import numpy as np
import pytest

from presto_tpu.session import Session

MV_SQL = ("SELECT k, count(*) AS c, count(v) AS cv, sum(v) AS sv, "
          "avg(x) AS ax, min(v) AS mn, max(x) AS mx, "
          "approx_distinct(v) AS ad, approx_percentile(x, 0.5) AS p50 "
          "FROM src GROUP BY k")


def _session(tmp_path, mode="dynamic"):
    s = Session()
    s.set("localfile_root", str(tmp_path))
    if mode == "distributed":
        s.set("distributed", True)
    else:
        s.set("execution_mode", mode)
    return s


def _append(s, name, rows):
    """Append host rows (None = NULL) straight onto a memory table —
    SQL INSERT has no null channel on raw-array sinks, so null-bearing
    test data takes the same path the catalog fixtures use."""
    t = s.catalog.get(name)
    arrays = {}
    for j, c in enumerate(t.schema):
        vals = [r[j] for r in rows]
        mask = np.array([v is None for v in vals])
        typ = t.schema[c]
        if typ.numpy_dtype() == object or not typ.is_numeric:
            base = np.array([("" if v is None else v) for v in vals],
                            dtype=object)
        else:
            base = np.array([(0 if v is None else v) for v in vals],
                            dtype=typ.numpy_dtype())
        arrays[c] = np.ma.masked_array(base, mask=mask) if mask.any() \
            else base
    t.append(arrays)


def _mk_src(s, connector="localfile"):
    """Source table: the localfile flavor exercises the MANIFEST delta
    path (no null channel, so no NULLs); the memory flavor exercises
    the row-count/delete-epoch watermark WITH null masks."""
    if connector == "memory":
        s.sql("CREATE TABLE src (k VARCHAR, v BIGINT, x DOUBLE)")
        _append(s, "src", [("a", 1, 1.5), ("a", 2, 2.5), ("b", 3, 3.5),
                           ("a", None, 4.5), (None, 5, None)])
    else:
        s.sql("CREATE TABLE src (k VARCHAR, v BIGINT, x DOUBLE) "
              "WITH (connector='localfile')")
        s.sql("INSERT INTO src VALUES ('a', 1, 1.5), ('a', 2, 2.5), "
              "('b', 3, 3.5), ('a', 4, 4.5), ('c', 5, 0.125)")


def _engine_rows(s, sql):
    s.set("materialized_view_routing", False)
    try:
        return s.sql(sql).rows
    finally:
        s.set("materialized_view_routing", True)


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------


def test_mv_lifecycle(tmp_path):
    s = _session(tmp_path)
    _mk_src(s)
    s.sql(f"CREATE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    rows = s.sql("SHOW MATERIALIZED VIEWS").rows
    assert rows == [("mv1", True, "src")]
    # backing tables are engine-internal
    assert all(not r[0].startswith("__mv__")
               for r in s.sql("SHOW TABLES").rows)
    with pytest.raises(Exception):
        s.sql(f"CREATE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    s.sql(f"CREATE MATERIALIZED VIEW IF NOT EXISTS mv1 AS {MV_SQL}")
    s.sql(f"CREATE OR REPLACE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    s.sql("DROP MATERIALIZED VIEW mv1")
    assert s.sql("SHOW MATERIALIZED VIEWS").rows == []
    with pytest.raises(Exception):
        s.sql("DROP MATERIALIZED VIEW mv1")
    s.sql("DROP MATERIALIZED VIEW IF EXISTS mv1")


def test_mv_name_cannot_shadow_table(tmp_path):
    s = _session(tmp_path)
    _mk_src(s)
    with pytest.raises(Exception):
        s.sql("CREATE MATERIALIZED VIEW src AS SELECT k, count(*) AS c "
              "FROM src GROUP BY k")


# ---------------------------------------------------------------------------
# refresh-merge identity (satellite: exact + sketch, modes x dtypes x
# masks x empty-delta x all-null-delta)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("connector", ["localfile", "memory"])
@pytest.mark.parametrize("mode", ["dynamic", "compiled", "distributed"])
def test_refresh_merge_identity_across_modes(tmp_path, mode, connector):
    s = _session(tmp_path, mode)
    _mk_src(s, connector)
    s.sql(f"CREATE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    probe = MV_SQL + " ORDER BY k"

    def check():
        routed = s.sql(probe)
        assert routed.stats.execution_mode == "mv_routed"
        assert routed.rows == _engine_rows(s, probe)
        # refresh-merged snapshot == full-recompute snapshot, column by
        # column including the sketch-estimate finals
        s.sql("CREATE OR REPLACE MATERIALIZED VIEW mv_full AS " + MV_SQL)
        a = s.sql("SELECT * FROM mv1 ORDER BY k").rows
        b = s.sql("SELECT * FROM mv_full ORDER BY k").rows
        assert a == b

    check()
    # append delta: new + existing groups, negatives, exact dyadics
    if connector == "memory":  # null masks ride the memory flavor
        _append(s, "src", [("b", 4, 4.5), ("c", -5, 5.5),
                           ("c", None, None), (None, 6, 0.25)])
    else:
        s.sql("INSERT INTO src VALUES ('b', 4, 4.5), ('c', -5, 5.5), "
              "('d', 6, 0.25)")
    r = s.sql("REFRESH MATERIALIZED VIEW mv1")
    assert r.rows[0][1] == "delta"
    check()
    # empty delta: refresh is a no-op
    r = s.sql("REFRESH MATERIALIZED VIEW mv1")
    assert r.rows == [(0, "noop")]
    if connector == "memory":
        # all-null delta: every aggregate argument NULL
        _append(s, "src", [("a", None, None), ("e", None, None)])
        r = s.sql("REFRESH MATERIALIZED VIEW mv1")
        assert r.rows[0][1] == "delta"
        check()
    # forced full recompute agrees with the merged state
    s.set("mv_refresh_mode", "full")
    r = s.sql("REFRESH MATERIALIZED VIEW mv1")
    assert r.rows[0][1] == "full"
    s.set("mv_refresh_mode", "auto")
    check()


def test_chunked_mode_routes_and_matches_exact(tpch_catalog_tiny,
                                               tmp_path):
    """Chunked execution only engages on bucketed device tables, so the
    chunked-mode leg rides tpch lineitem: the un-routed probe must
    actually run CHUNKED, while the MV-routed answer must equal the
    exact single-pass result.  Grouping by l_suppkey keeps the group
    count under the single-pass register-shrink threshold (8192 groups
    at m=1024, where HLL mode-identity intentionally ends) and
    quantity's distinct values per group under the summary capacity,
    so the stored sketch states are exact and both sketch readouts
    match the engine bit-for-bit.  (Chunked percentile itself is only
    rank-error-bounded — see test_approx_aggregates — which is why the
    identity oracle here is the exact path, not the chunked one.)"""
    import presto_tpu

    mv_sql = ("SELECT l_suppkey, count(*) AS c, avg(l_quantity) AS aq, "
              "approx_distinct(l_partkey) AS ad, "
              "approx_percentile(l_quantity, 0.5) AS p50 "
              "FROM lineitem GROUP BY l_suppkey")
    probe = mv_sql + " ORDER BY l_suppkey"
    chunked = presto_tpu.connect(tpch_catalog_tiny)
    chunked.set("execution_mode", "chunked")
    chunked.properties["chunked_rows_threshold"] = 50_000
    chunked.set("localfile_root", str(tmp_path))
    exact = presto_tpu.connect(tpch_catalog_tiny)
    try:
        chunked.sql("CREATE MATERIALIZED VIEW mv_li "
                    "WITH (connector='memory') AS " + mv_sql)
        routed = chunked.sql(probe)
        assert routed.stats.execution_mode == "mv_routed"
        engine = chunked.sql(probe)  # cached matview still routes
        assert engine.stats.execution_mode == "mv_routed"
        un_routed = _engine_rows(chunked, probe)
        assert chunked.sql(probe).rows == routed.rows
        # the un-routed probe really exercised the chunked runner
        chunked.set("materialized_view_routing", False)
        assert chunked.sql(probe).stats.execution_mode == "chunked"
        chunked.set("materialized_view_routing", True)
        # identity oracle: the exact single-pass engine
        assert routed.rows == _engine_rows(exact, probe)
        # chunked exact aggregates agree; sketch columns are bounded,
        # not identical, on the chunked path
        assert [r[:3] for r in un_routed] == [r[:3] for r in routed.rows]
        # immutable source: refresh is a clean no-op
        assert chunked.sql("REFRESH MATERIALIZED VIEW mv_li"
                           ).rows == [(0, "noop")]
    finally:
        chunked.sql("DROP MATERIALIZED VIEW IF EXISTS mv_li")


def test_refresh_merge_identity_int_key_dtypes(tmp_path):
    """Non-string keys + BIGINT/DOUBLE aggregate args; values chosen
    exactly representable so '==' is a fair comparison."""
    s = _session(tmp_path)
    s.sql("CREATE TABLE src (k BIGINT, v BIGINT, x DOUBLE)")
    _append(s, "src", [(10, 100, 0.5), (10, 200, 1.5),
                       (20, 300, 2.25), (20, None, None)])
    s.sql(f"CREATE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    s.sql("INSERT INTO src VALUES (20, 400, 3.75), (30, 500, 4.0)")
    assert s.sql("REFRESH MATERIALIZED VIEW mv1").rows[0][1] == "delta"
    probe = MV_SQL + " ORDER BY k"
    assert s.sql(probe).rows == _engine_rows(s, probe)


def test_refresh_delta_cost_scales_with_delta(tmp_path):
    """The tentpole economics: a refresh after ONE appended file scans
    one split while the source holds many (mv_delta_splits <<
    mv_source_splits)."""
    s = _session(tmp_path)
    s.sql("CREATE TABLE src (k VARCHAR, v BIGINT, x DOUBLE) "
          "WITH (connector='localfile')")
    for i in range(6):
        s.sql(f"INSERT INTO src VALUES ('g{i % 2}', {i}, {i}.5)")
    s.sql(f"CREATE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    s.sql("INSERT INTO src VALUES ('g0', 99, 9.5)")
    r = s.sql("REFRESH MATERIALIZED VIEW mv1")
    assert r.rows[0][1] == "delta"
    assert r.stats.mv_refresh_delta == 1
    assert r.stats.mv_delta_splits == 1
    assert r.stats.mv_source_splits >= 6
    assert r.stats.mv_delta_splits < r.stats.mv_source_splits


def test_refresh_degrades_loudly_on_delete(tmp_path):
    s = _session(tmp_path)
    _mk_src(s)
    s.sql(f"CREATE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    s.sql("DELETE FROM src WHERE v = 1")
    r = s.sql("REFRESH MATERIALIZED VIEW mv1")
    assert r.rows[0][1].startswith("full:")  # the loud part
    assert r.stats.mv_refresh_full == 1
    assert r.stats.mv_refresh_delta == 0
    probe = MV_SQL + " ORDER BY k"
    assert s.sql(probe).rows == _engine_rows(s, probe)  # never wrong
    # delta-forced mode refuses instead of silently recomputing
    s.sql("DELETE FROM src WHERE v = 2")
    s.set("mv_refresh_mode", "delta")
    with pytest.raises(Exception, match="delta"):
        s.sql("REFRESH MATERIALIZED VIEW mv1")


# ---------------------------------------------------------------------------
# chaos: fault mid-merge leaves the prior snapshot serving
# ---------------------------------------------------------------------------


def test_chaos_fault_mid_merge_keeps_prior_snapshot(tmp_path):
    s = _session(tmp_path)
    _mk_src(s)
    s.sql(f"CREATE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    probe = MV_SQL + " ORDER BY k"
    before = s.sql(probe).rows
    backing = s.catalog.tables["__mv__mv1"]

    s.sql("INSERT INTO src VALUES ('z', 42, 42.5)")
    real = backing._sink_write_file

    def boom(*a, **kw):
        raise OSError("injected mid-merge fault")

    backing._sink_write_file = boom
    try:
        with pytest.raises(Exception):
            s.sql("REFRESH MATERIALIZED VIEW mv1")
    finally:
        backing._sink_write_file = real
    # prior snapshot intact: routed rows unchanged, no staged debris,
    # no watermark stamp leaked into a future commit
    assert s.sql(probe).rows == before
    assert not glob.glob(os.path.join(backing.dir, "*.stg"))
    assert getattr(backing, "_mv_stamp", None) is None
    # the interrupted refresh retries cleanly and lands the delta
    r = s.sql("REFRESH MATERIALIZED VIEW mv1")
    assert r.rows[0][1] == "delta"
    assert s.sql(probe).rows == _engine_rows(s, probe)


def test_chaos_prior_snapshot_rows_stable(tmp_path):
    """Sharper form of the above: the routed rows after the fault are
    EXACTLY the pre-fault rows (old watermark, old data)."""
    s = _session(tmp_path)
    _mk_src(s)
    s.sql(f"CREATE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    before = s.sql("SELECT * FROM mv1 ORDER BY k").rows
    backing = s.catalog.tables["__mv__mv1"]
    s.sql("INSERT INTO src VALUES ('z', 42, 42.5)")
    backing._sink_write_file = lambda *a, **kw: (_ for _ in ()).throw(
        OSError("injected"))
    try:
        with pytest.raises(Exception):
            s.sql("REFRESH MATERIALIZED VIEW mv1")
    finally:
        del backing._sink_write_file
    assert s.sql("SELECT * FROM mv1 ORDER BY k").rows == before


# ---------------------------------------------------------------------------
# satellite: reader mid-poll across TWO consecutive refreshes
# ---------------------------------------------------------------------------


def test_mv_reader_survives_two_refresh_cutovers(tmp_path):
    """A long-poll reader resolves the backing's file list, then TWO
    refresh cut-overs land before it opens the files.  retire_depth=2
    on MV backing keeps each retired generation through the NEXT commit
    too, so every file in the captured list still exists; a third
    cut-over may finally GC them (bounded, not leaked)."""
    s = _session(tmp_path)
    _mk_src(s)
    s.sql(f"CREATE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    backing = s.catalog.tables["__mv__mv1"]
    assert backing.retire_depth == 2
    polled = [os.path.join(backing.dir, p)
              for p in backing._manifest["shards"]]
    assert polled and all(os.path.exists(p) for p in polled)

    for i in (101, 102):  # two consecutive refresh cut-overs
        s.sql(f"INSERT INTO src VALUES ('r', {i}, {i}.5)")
        assert s.sql("REFRESH MATERIALIZED VIEW mv1").rows[0][1] \
            == "delta"
        # mid-poll guarantee: the OLD file list is still fully on disk
        assert all(os.path.exists(p) for p in polled), \
            f"refresh #{i - 100} broke a mid-poll reader's file list"
    # and the reader's data is actually readable end to end
    from presto_tpu.storage.shard import ShardReader

    for p in polled:
        ShardReader(p).read(None)
    # GC is deferred, not disabled: two MORE cut-overs retire them
    for i in (103, 104):
        s.sql(f"INSERT INTO src VALUES ('r', {i}, {i}.5)")
        s.sql("REFRESH MATERIALIZED VIEW mv1")
    assert not all(os.path.exists(p) for p in polled)


def test_regular_table_gc_still_one_generation(tmp_path):
    """Regression guard for the pre-existing behavior: NON-MV localfile
    tables still GC retired files after ONE generation (retire_depth
    stays 1) — a file retired by a replace commit survives that commit
    and is removed by the next GC-ing commit (DELETE rewrites never GC
    so a transaction can roll back; sink commits do)."""
    s = _session(tmp_path)
    s.sql("CREATE TABLE t (x BIGINT) WITH (connector='localfile')")
    s.sql("INSERT INTO t VALUES (1), (2), (3)")
    t = s.catalog.tables["t"]
    assert getattr(t, "retire_depth", 1) == 1
    old = [os.path.join(t.dir, p) for p in t._manifest["shards"]]
    assert old
    s.sql("DELETE FROM t WHERE x = 1")   # replace commit: retires old
    assert all(os.path.exists(p) for p in old)
    s.sql("INSERT INTO t VALUES (9)")    # next sink commit: GCs them
    assert not any(os.path.exists(p) for p in old)


# ---------------------------------------------------------------------------
# serving: the containment matcher
# ---------------------------------------------------------------------------


def test_routing_containment_matrix(tmp_path):
    s = _session(tmp_path)
    _mk_src(s)
    s.sql(f"CREATE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    routed_cases = [
        # same grain
        "SELECT k, count(*) AS c FROM src GROUP BY k ORDER BY k",
        # rollup to the global grain (HLL union via stored registers,
        # KLL re-summarize) + percentile the MV never stored
        "SELECT count(*) AS c, sum(v) AS sv, approx_distinct(v) AS ad "
        "FROM src",
        "SELECT approx_percentile(x, 0.9) AS p90 FROM src",
        # predicate subsumption: extra equality on a key column
        "SELECT k, sum(v) AS sv FROM src WHERE k = 'a' GROUP BY k",
        "SELECT count(*) AS c FROM src WHERE k IN ('a', 'b')",
        "SELECT count(*) AS c FROM src WHERE k IS NOT NULL",
        # ORDER BY + LIMIT host-side
        "SELECT k, max(x) AS mx FROM src GROUP BY k ORDER BY k DESC "
        "LIMIT 2",
    ]
    for sql in routed_cases:
        r = s.sql(sql)
        assert r.stats.execution_mode == "mv_routed", sql
        assert r.rows == _engine_rows(s, sql), sql
    declined_cases = [
        "SELECT k, sum(x) AS sx FROM src GROUP BY k",   # agg not stored
        "SELECT v, count(*) AS c FROM src GROUP BY v",  # non-key group
        "SELECT k, count(*) AS c FROM src WHERE v > 1 GROUP BY k",
        "SELECT k, count(DISTINCT v) AS c FROM src GROUP BY k",
        # different register count than the stored HLL state
        "SELECT approx_distinct(v, 0.01) AS ad FROM src",
    ]
    for sql in declined_cases:
        r = s.sql(sql)
        assert r.stats.execution_mode != "mv_routed", sql
        assert r.rows == _engine_rows(s, sql), sql


def test_routing_counts_and_kill_switches(tmp_path, monkeypatch):
    s = _session(tmp_path)
    _mk_src(s)
    s.sql(f"CREATE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    sql = "SELECT k, count(*) AS c FROM src GROUP BY k"
    r = s.sql(sql)
    assert r.stats.execution_mode == "mv_routed"
    assert r.stats.mv_routed == 1
    s.set("materialized_view_routing", False)
    assert s.sql(sql).stats.execution_mode != "mv_routed"
    s.set("materialized_view_routing", True)
    monkeypatch.setenv("PRESTO_TPU_MV_ROUTING", "off")
    assert s.sql(sql).stats.execution_mode != "mv_routed"
    monkeypatch.delenv("PRESTO_TPU_MV_ROUTING")
    assert s.sql(sql).stats.execution_mode == "mv_routed"


def test_routing_serves_latest_snapshot_and_writes_invalidate(tmp_path):
    """Engine writes to the source do NOT silently change routed
    results (MV staleness is by design, refresh is the cut-over), and a
    refresh immediately flips what routing serves."""
    s = _session(tmp_path)
    _mk_src(s)
    s.sql(f"CREATE MATERIALIZED VIEW mv1 AS {MV_SQL}")
    sql = "SELECT k, count(*) AS c FROM src GROUP BY k ORDER BY k"
    before = s.sql(sql).rows
    s.sql("INSERT INTO src VALUES ('b', 7, 7.5)")
    assert s.sql(sql).rows == before  # stale until refreshed, by design
    s.sql("REFRESH MATERIALIZED VIEW mv1")
    after = s.sql(sql).rows
    assert after != before
    assert after == _engine_rows(s, sql)


def test_non_mergeable_mv_full_refresh_and_exact_match(tmp_path):
    s = _session(tmp_path)
    _mk_src(s)
    sql = ("SELECT k, count(*) AS c FROM src GROUP BY k HAVING "
           "count(*) > 1")
    s.sql(f"CREATE MATERIALIZED VIEW mvh AS {sql}")
    rows = s.sql("SHOW MATERIALIZED VIEWS").rows
    assert rows[0][0] == "mvh" and rows[0][1] is False
    r = s.sql(sql)  # structurally identical -> served from the MV
    assert r.stats.execution_mode == "mv_routed"
    assert r.rows == _engine_rows(s, sql)
    s.sql("INSERT INTO src VALUES ('b', 8, 8.5)")
    r = s.sql("REFRESH MATERIALIZED VIEW mvh")
    assert r.rows[0][1].startswith("full")  # loud: not mergeable
    assert s.sql(sql).rows == _engine_rows(s, sql)


def test_memory_source_delete_epoch_degrades(tmp_path):
    """In-memory sources have no manifest; the delete epoch + row count
    watermark still classifies appends vs destructive changes."""
    s = _session(tmp_path)
    s.sql("CREATE TABLE m (k VARCHAR, v BIGINT)")
    s.sql("INSERT INTO m VALUES ('a', 1), ('b', 2)")
    s.sql("CREATE MATERIALIZED VIEW mvm AS SELECT k, sum(v) AS sv "
          "FROM m GROUP BY k")
    s.sql("INSERT INTO m VALUES ('a', 3)")
    assert s.sql("REFRESH MATERIALIZED VIEW mvm").rows[0][1] == "delta"
    s.sql("DELETE FROM m WHERE v = 1")
    r = s.sql("REFRESH MATERIALIZED VIEW mvm")
    assert r.rows[0][1].startswith("full:")
    probe = "SELECT k, sum(v) AS sv FROM m GROUP BY k ORDER BY k"
    assert s.sql(probe).rows == _engine_rows(s, probe)
