"""Second-oracle verifier for the TPC-DS queries whose sqlite oracle is
BUILDER-REWRITTEN SQL (tests/tpcds_queries.py SQLITE_OVERRIDES — e.g. the
hand-expanded ROLLUP unions) plus q89's widened-tolerance case (round-3
VERDICT item 6: a rewrite bug could mask an engine bug when only one
oracle exists).

Reference analog: presto-verifier runs each query against two independent
clusters and compares row checksums (presto-verifier/.../checksum/).
Here the two "clusters" are the engine's independent execution paths —
per-op dynamic dispatch vs the whole-fragment compiled executor vs the
8-virtual-device distributed mesh — which share the planner but nothing
below it.  The rewritten sqlite text plays no part, so agreement is an
independent second opinion on exactly the queries the rewrites cover.
"""

import pytest

import presto_tpu
from presto_tpu.catalog import tpcds_catalog
from tests.tpcds_queries import QUERIES, SQLITE_OVERRIDES

SF = 0.01
VERIFY_QIDS = sorted(SQLITE_OVERRIDES) + [89]


def _norm_rows(rows):
    """Order-insensitive normalized rows: floats rounded to absorb
    summation-order ULP noise between executors."""
    out = []
    for r in rows:
        out.append(tuple(round(v, 4) if isinstance(v, float) else v
                         for v in r))
    return sorted(out, key=repr)


def _checksum(rows):
    import hashlib

    h = hashlib.sha256()
    for r in _norm_rows(rows):
        h.update(repr(r).encode())
    return h.hexdigest()


@pytest.fixture(scope="module")
def sessions():
    cat = tpcds_catalog(SF, cache_dir="/tmp/presto_tpu_cache")
    dyn = presto_tpu.connect(cat)
    dyn.set("execution_mode", "dynamic")
    comp = presto_tpu.connect(cat)
    comp.set("execution_mode", "auto")
    dist = presto_tpu.connect(cat)
    dist.set("distributed", True)
    return dyn, comp, dist


# the distributed leg recompiles an 8-device mesh program per query
# (~minutes each on the CPU test mesh); a rotating sample keeps suite
# wall-clock bounded while every query still gets the dynamic/compiled
# cross-check
DIST_QIDS = VERIFY_QIDS[::5]


# q14's distributed leg alone compiles ~10 minutes of 8-device mesh
# program on the 1-core CI box (q67's ~30s); their dynamic/compiled
# legs are covered by test_tpcds.py and q87 keeps the verifier's mesh
# leg exercised in tier 1
# round 12 adds 77/80/22 to the tier-2 set: together ~45s of re-verify
# on the 1-core box, and their dynamic/compiled legs stay covered by
# test_tpcds.py every run (budget fit for the fragment-fusion tier-1
# additions; the full verifier corpus still runs in tier 2)
@pytest.mark.parametrize("qid", [
    pytest.param(q, marks=pytest.mark.slow)
    if q in (14, 67, 77, 80, 22) else q
    for q in VERIFY_QIDS])
def test_override_query_checksum_across_executors(sessions, qid):
    dyn, comp, dist = sessions
    sql = QUERIES[qid]
    rows_dyn = dyn.sql(sql).rows
    assert rows_dyn, f"q{qid}: empty result verifies nothing"
    cs_dyn = _checksum(rows_dyn)
    cs_comp = _checksum(comp.sql(sql).rows)
    assert cs_dyn == cs_comp, f"q{qid}: dynamic vs compiled disagree"
    if qid in DIST_QIDS:
        # distributed mesh: falls back identically when a shape cannot
        # distribute, which still exercises an independent code path
        cs_dist = _checksum(dist.sql(sql).rows)
        assert cs_dyn == cs_dist, \
            f"q{qid}: dynamic vs distributed disagree"


@pytest.mark.slow
def test_q67_agg_economics_counters(sessions):
    """Adaptive-agg economics on the verifier sweep's worst shape
    (ISSUE 13): q67's rollup expansion is all high-cardinality GROUP
    BYs — every executed grouped aggregate must carry a planned
    strategy (the agg_strategy counter), the checksum must agree
    between the dynamic and compiled executors WITH the adaptive
    machinery armed, and the kill switch must not change results."""
    dyn, comp, _dist = sessions
    sql = QUERIES[67]
    r = dyn.sql(sql)
    assert r.rows, "q67: empty result verifies nothing"
    assert r.stats.agg_strategy, \
        "q67 executed grouped aggregates without a strategy count"
    assert sum(r.stats.agg_strategy.values()) >= 1
    assert set(r.stats.agg_strategy) <= {"one_pass", "final_only",
                                         "two_phase"}
    cs = _checksum(r.rows)
    assert cs == _checksum(comp.sql(sql).rows), \
        "q67: dynamic vs compiled disagree with adaptive agg on"
    dyn.set("adaptive_partial_agg", False)
    try:
        assert cs == _checksum(dyn.sql(sql).rows), \
            "q67: adaptive_partial_agg on==off checksums differ"
    finally:
        dyn.set("adaptive_partial_agg", True)
