"""SF1 correctness pass (nightly tier): capacity guards, Grace-hash
spill, key packing, and chunked execution at non-toy scale.

Reference: presto-tests' TestDistributedSpilledQueries pattern — the
same queries, re-run with memory limits forcing the spill paths.

Slow (~minutes on CPU): runs only when PRESTO_TPU_SCALE_TESTS=1
(the default `pytest tests/` stays fast).  The bench driver and
nightly-style runs set it.
"""

import os

import pytest

import presto_tpu
from presto_tpu.catalog import tpch_catalog

from tpch_queries import QUERIES

pytestmark = pytest.mark.skipif(
    os.environ.get("PRESTO_TPU_SCALE_TESTS") != "1",
    reason="SF1 scale tier: set PRESTO_TPU_SCALE_TESTS=1")

SF = 1.0


@pytest.fixture(scope="module")
def sf1_session():
    return presto_tpu.connect(tpch_catalog(SF, "/tmp/presto_tpu_cache"))


@pytest.fixture(scope="module")
def sf1_ref(sf1_session):
    # independent session, same catalog: different execution paths below
    return presto_tpu.connect(sf1_session.catalog)


def norm(rows):
    return [tuple(round(v, 1) if isinstance(v, float) else v for v in r)
            for r in rows]


@pytest.mark.parametrize("qid", [1, 3, 4, 6, 12, 13, 14, 18])
def test_sf1_compiled_vs_dynamic(sf1_session, sf1_ref, qid):
    """Static-capacity guards and key packing at SF1 row counts: the
    compiled path must agree with dynamic eager execution."""
    sf1_ref.properties["execution_mode"] = "dynamic"
    got = sf1_session.sql(QUERIES[qid])
    want = sf1_ref.sql(QUERIES[qid])
    assert norm(got.rows) == norm(want.rows)


def test_sf1_chunked_matches_whole(sf1_session):
    """Chunked (grouped) execution at SF1: forces multi-chunk runs with
    real partial states across chunk boundaries."""
    s = presto_tpu.connect(sf1_session.catalog)
    s.properties["chunked_rows_threshold"] = 1_000_000
    s.properties["chunk_orders"] = 400_000  # ~4 chunks
    for qid in (1, 3, 18):
        got = s.sql(QUERIES[qid])
        want = sf1_session.sql(QUERIES[qid])
        assert norm(got.rows) == norm(want.rows), f"Q{qid}"


def test_sf1_spill_join(sf1_session):
    """Grace-hash spill path under a tight memory budget at SF1."""
    s = presto_tpu.connect(sf1_session.catalog)
    s.properties["execution_mode"] = "dynamic"
    s.properties["query_max_memory_bytes"] = 256 * 1024 * 1024
    s.properties["spill_enabled"] = True
    q = ("SELECT o_orderpriority, count(*) AS c FROM orders, lineitem "
         "WHERE o_orderkey = l_orderkey AND l_quantity > 45 "
         "GROUP BY o_orderpriority ORDER BY 1")
    got = s.sql(q)
    want = sf1_session.sql(q)
    assert norm(got.rows) == norm(want.rows)
