"""Native C++ data plane tests: codec roundtrips (incl. fuzz), known
hash vectors, encodings, serde framing, and spill integration.

Reference analog: the PagesSerde/compression tests in
presto-main/src/test/java/.../execution/buffer/TestPagesSerde.java.
"""

import numpy as np
import pytest

from presto_tpu import native
from presto_tpu.native import serde


def test_native_available():
    # the image ships g++; the native path must actually build
    assert native.available()


def test_xxh64_vectors():
    # spec vectors pin the implementation to real xxHash64
    assert native.xxh64(b"") == 0xEF46DB3751D8E999
    assert native.xxh64(b"abc") == 0x44BC2CF5AD770999


@pytest.mark.parametrize("seed", range(6))
def test_lz4_fuzz_roundtrip(seed):
    rng = np.random.default_rng(seed)
    kind = seed % 3
    n = int(rng.integers(0, 300_000))
    if kind == 0:  # highly compressible
        data = bytes(rng.integers(0, 4, n, dtype=np.uint8))
    elif kind == 1:  # incompressible
        data = rng.bytes(n)
    else:  # runs + structure
        data = np.repeat(rng.integers(0, 255, max(n // 64, 1), dtype=np.uint8),
                         64)[:n].tobytes()
    c = native.lz4_compress(data)
    assert c is not None
    assert native.lz4_decompress(c, len(data)) == data


def test_lz4_corruption_never_crashes():
    # the block format carries no checksum (corruption detection is the
    # PTPG frame's xxh64, tested below); the decoder's contract under
    # corruption is: no crash / no overrun — either a clean error or a
    # same-length-but-different output.
    data = b"the quick brown fox " * 100
    c = bytearray(native.lz4_compress(data))
    for pos in range(0, len(c), 7):
        bad = bytearray(c)
        bad[pos] ^= 0xFF
        try:
            out = native.lz4_decompress(bytes(bad), len(data))
        except ValueError:
            continue
        assert len(out) == len(data)


def test_delta_pack_roundtrip():
    rng = np.random.default_rng(1)
    a = np.cumsum(rng.integers(-1000, 1000, 50_000)).astype(np.int64)
    packed = native.delta_pack(a)
    assert packed is not None
    data, width, base = packed
    assert (native.delta_unpack(data, width, base, len(a)) == a).all()
    assert len(data) < a.nbytes // 2


def test_delta_pack_declines_wide():
    # random 64-bit values: width > 56 -> plain encoding upstream
    rng = np.random.default_rng(2)
    a = rng.integers(-(2**62), 2**62, 1000, dtype=np.int64)
    assert native.delta_pack(a) is None


def test_rle_roundtrip():
    a = np.repeat(np.arange(100, dtype=np.int64), 77)
    enc = native.rle_encode(a)
    assert enc is not None
    values, runs = enc
    assert len(values) == 100
    assert (native.rle_decode(values, runs, len(a)) == a).all()


def test_dict_encode_matches_numpy():
    rng = np.random.default_rng(3)
    strs = np.array(
        ["k%04d" % v for v in rng.integers(0, 500, 20_000)], dtype=object)
    out = native.dict_encode(strs)
    assert out is not None
    codes, uniq = out
    ref_uniq, ref_codes = np.unique(strs.astype(str), return_inverse=True)
    assert (uniq.astype(str) == ref_uniq).all()
    assert (codes == ref_codes).all()


def test_minmax_gather_sel():
    rng = np.random.default_rng(6)
    a = rng.integers(-10_000, 10_000, 5000).astype(np.int64)
    assert native.minmax(a) == (int(a.min()), int(a.max()))
    f = rng.random(5000)
    lo, hi = native.minmax(f)
    assert lo == f.min() and hi == f.max()
    assert native.minmax(np.empty(0, np.int64)) == (None, None)
    mask = rng.random(5000) < 0.2
    idx = native.sel_to_idx(mask)
    assert (idx == np.flatnonzero(mask)).all()
    for dt in (np.int64, np.int32, np.float64, np.bool_):
        col = a.astype(dt)
        assert (native.gather(col, idx) == col[idx]).all()


def test_serde_stream_roundtrip(tmp_path):
    rng = np.random.default_rng(7)
    cols = {"a": np.cumsum(rng.integers(0, 9, 20_000)).astype(np.int64),
            "b": rng.random(20_000),
            "c": (rng.random(20_000) < 0.5)}
    p = tmp_path / "stream.ptpg"
    with open(p, "wb") as f:
        n = serde.write_stream(f, cols)
    assert n == p.stat().st_size
    with open(p, "rb") as f:
        back = serde.read_stream(f)
    for k, v in cols.items():
        assert (back[k] == v).all()


def test_serde_roundtrip_and_checksum():
    rng = np.random.default_rng(4)
    cols = {
        "i64": np.cumsum(rng.integers(0, 50, 10_000)).astype(np.int64),
        "f64": rng.random(10_000),
        "i32": rng.integers(0, 7, 10_000).astype(np.int32),
        "mask": rng.random(10_000) < 0.5,
        "empty": np.empty(0, dtype=np.float64),
    }
    buf = serde.serialize_columns(cols)
    back = serde.deserialize_columns(buf)
    for k, v in cols.items():
        assert back[k].dtype == v.dtype
        assert (back[k] == v).all()
    # flip one payload byte -> checksum must catch it
    bad = bytearray(buf)
    bad[len(bad) // 2] ^= 0x01
    with pytest.raises(ValueError):
        serde.deserialize_columns(bytes(bad))


def test_spiller_uses_native_frames(tmp_path):
    from presto_tpu import types as T
    from presto_tpu.batch import batch_from_numpy
    from presto_tpu.memory.spill import FileSpiller

    rng = np.random.default_rng(5)
    b = batch_from_numpy(
        {"x": rng.integers(0, 1000, 5000).astype(np.int64),
         "s": np.array(["v%d" % v for v in rng.integers(0, 30, 5000)], dtype=object)},
        {"x": T.BIGINT, "s": T.VARCHAR},
    )
    sp = FileSpiller(str(tmp_path))
    handle = sp.spill(b)
    assert handle.endswith(".ptpg")
    back = sp.unspill(handle)
    assert (np.asarray(back.columns["x"].data) == np.asarray(b.columns["x"].data)).all()
    assert (np.asarray(back.columns["s"].data) == np.asarray(b.columns["s"].data)).all()
    assert back.columns["s"].dictionary is b.columns["s"].dictionary
    sp.close()
