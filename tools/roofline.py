"""Roofline accounting for the engine's hot kernels on the real chip.

Round-3 VERDICT weak #5: the headline rows/sec number had no in-repo
framing against what the hardware can actually do.  An analytic SQL
engine on TPU is HBM-BANDWIDTH bound (scans, sorts, gathers — there are
almost no matmuls), so the roofline that matters is bytes/sec, not MXU
FLOPs; "MFU" here is achieved HBM bandwidth / peak HBM bandwidth.

Methodology for a TUNNELED device (the axon RTT is ~100ms, far above
kernel times): every measurement runs K iterations INSIDE one jitted
program (lax.fori_loop with a loop-carried dependence so XLA cannot
hoist), returns a scalar, and subtracts the measured empty-program
round trip; per-iteration time = (t - t_rtt) / K.

Prints ONE JSON line; run `python tools/roofline.py` on the chip.
The numbers land in docs/PERF.md.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K = 30


def timed(fn, *args, runs=3):
    """Best wall time of fn(*args) -> scalar, forced to host."""
    float(fn(*args))  # compile + warm
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        float(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def exchange_sweep(per_iter, rng):
    """Exchange economics: host HTTP shuffle vs in-trace all_to_all.

    Anchors the fragment-fusion cost model (plan/fusion_cost.py): what
    one repartition edge costs on the per-fragment HTTP path (pack PTPG
    page -> loopback POST -> GET -> unpack -> host hash_partition — the
    floor; real DCN adds network) vs lowered into the traced program as
    ONE lax.all_to_all over the mesh.  Swept rows x ndev; cells the
    host can't run (fewer local devices than ndev) are skipped.  The
    `--calibrate` mode fits these cells into a per-platform fusion
    profile (least-squares intercept + slope per lane)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from presto_tpu.batch import Batch as PBatch
    from presto_tpu.parallel import cluster as CL
    from presto_tpu.parallel import exchange as EXC
    from presto_tpu.parallel.mesh import AXIS, make_mesh
    from presto_tpu.parallel import dist_executor as DX
    from jax.sharding import NamedSharding, PartitionSpec as PSpec

    import threading
    import urllib.request
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    page_store = {}

    class _Echo(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_POST(self):
            page_store["page"] = self.rfile.read(
                int(self.headers.get("Content-Length", 0)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def do_GET(self):
            body = page_store.get("page", b"")
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    echo = ThreadingHTTPServer(("127.0.0.1", 0), _Echo)
    threading.Thread(target=echo.serve_forever, daemon=True).start()
    echo_url = f"http://127.0.0.1:{echo.server_address[1]}/page"

    ndev_avail = len(jax.devices())
    xout = {}
    for rexp in (16, 18, 20):
        rows = 1 << rexp
        kh = rng.integers(0, 1 << 31, rows).astype(np.int64)
        vh = rng.normal(size=rows)
        cols = {"k": (kh, None), "v": (vh, None)}
        cell = {"bytes": int(kh.nbytes + vh.nbytes)}

        def host_trip(nd):
            page = CL.pack_columns(cols)
            req = urllib.request.Request(echo_url, data=page,
                                         method="POST")
            urllib.request.urlopen(req, timeout=30).read()
            body = urllib.request.urlopen(echo_url, timeout=30).read()
            out_cols = CL.unpack_columns(body)
            CL.hash_partition(out_cols, ["k"], nd)

        for nd in (2, 4, 8):
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                host_trip(nd)
                best = min(best, time.perf_counter() - t0)
            cell[f"host_nd{nd}_ms"] = round(best * 1000, 2)
            if nd > ndev_avail:
                cell[f"coll_nd{nd}_ms"] = None  # not enough devices
                continue
            mesh = make_mesh(nd)
            spec = NamedSharding(mesh, PSpec(AXIS))
            kd = jax.device_put(kh, spec)
            vd = jax.device_put(vh, spec)

            def inner(k, v):
                from presto_tpu import types as _PT
                from presto_tpu.batch import Column as _PCol

                def body(i, s):
                    b = PBatch(
                        {"k": _PCol(k ^ s, None, _PT.BIGINT, None),
                         "v": _PCol(v, None, _PT.DOUBLE, None)},
                        jnp.ones(k.shape, bool))
                    ob, _ov = EXC.repartition_batch(
                        b, [b.columns["k"]], nd, AXIS)
                    # REAL loop-carried dep through the exchanged data
                    # (a maskable dep lets XLA DCE the all_to_all)
                    return s + ob.columns["k"].data[0]
                return lax.fori_loop(0, K, body, jnp.int64(0))

            coll = jax.jit(DX._shard_mapped(
                inner, mesh, (PSpec(AXIS), PSpec(AXIS)), PSpec()))
            t = per_iter(timed(coll, kd, vd))
            cell[f"coll_nd{nd}_ms"] = round(t * 1000, 2)
        xout[f"r{rows >> 10}k"] = cell
    echo.shutdown()
    return xout


def dcn_child(coord, nproc, pid, ldev):
    """`--dcn-child` (spawned by dcn_sweep, never by hand): process
    `pid` of an `nproc`-process jax.distributed CPU mesh with `ldev`
    virtual local devices, timing the SAME repartition fori_loop the
    exchange sweep uses — but over the GLOBAL mesh, so every
    all_to_all crosses process boundaries through gloo loopback (the
    CI stand-in for the TPU DCN fabric).  Rank 0 prints ONE JSON line
    {"r64k": ms_per_iter, ...}; other ranks print nothing."""
    import numpy as np

    from presto_tpu.parallel import mesh as MH

    MH.init_multihost(coord, nproc, pid)

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as PSpec

    import presto_tpu  # noqa: F401  (x64 + compile cache)
    from presto_tpu.batch import Batch as PBatch
    from presto_tpu.parallel import dist_executor as DX
    from presto_tpu.parallel import exchange as EXC
    from presto_tpu.parallel.mesh import AXIS, make_mesh

    nd = nproc * ldev
    mesh = make_mesh(nd)
    rng = np.random.default_rng(0)
    rtt = timed(jax.jit(lambda x: x + 1.0), jnp.float32(1.0))
    out = {}
    for rexp in (16, 18, 20):
        rows = 1 << rexp
        kh = rng.integers(0, 1 << 31, rows).astype(np.int64)
        vh = rng.normal(size=rows)
        spec = NamedSharding(mesh, PSpec(AXIS))
        kd = DX._put(kh, spec)
        vd = DX._put(vh, spec)

        def inner(k, v):
            from presto_tpu import types as _PT
            from presto_tpu.batch import Column as _PCol

            def body(i, s):
                b = PBatch(
                    {"k": _PCol(k ^ s, None, _PT.BIGINT, None),
                     "v": _PCol(v, None, _PT.DOUBLE, None)},
                    jnp.ones(k.shape, bool))
                ob, _ov = EXC.repartition_batch(
                    b, [b.columns["k"]], nd, AXIS)
                return s + ob.columns["k"].data[0]
            return lax.fori_loop(0, K, body, jnp.int64(0))

        coll = jax.jit(DX._shard_mapped(
            inner, mesh, (PSpec(AXIS), PSpec(AXIS)), PSpec()))
        t = max(timed(coll, kd, vd) - rtt, 1e-9) / K
        out[f"r{rows >> 10}k"] = round(t * 1000, 2)
    if pid == 0:
        print(json.dumps(out), flush=True)


def dcn_sweep(nprocs=(2, 4), local_devices=2):
    """Multi-process collective lane: for each process count, boot that
    many `--dcn-child` subprocesses as one jax.distributed mesh and
    collect rank 0's per-iteration all_to_all walls.  Returns cells
    keyed like the exchange sweep ({"r64k": {"dcn_np2_ms": ..}, ...});
    a process count that fails to boot (no gloo, port trouble) is
    skipped — calibration degrades, never fails."""
    import socket
    import subprocess

    cells = {}
    for nproc in nprocs:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count="
                     f"{local_devices}"])
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--dcn-child",
             f"127.0.0.1:{port}", str(nproc), str(pid),
             str(local_devices)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env) for pid in range(nproc)]
        try:
            line = procs[0].communicate(timeout=600)[0].strip()
            for p in procs[1:]:
                p.communicate(timeout=60)
            walls = json.loads(line.splitlines()[-1])
        except Exception:  # noqa: BLE001 — skip the lane, keep priors
            for p in procs:
                p.kill()
            print(json.dumps({"dcn_skipped": nproc}),
                  file=sys.stderr, flush=True)
            continue
        for label, ms in walls.items():
            cells.setdefault(label, {})[f"dcn_np{nproc}_ms"] = ms
    return cells


def calibrate(out_path=None, multiproc=False):
    """`tools/roofline.py --calibrate [--multiproc] [out.json]`: run
    ONLY the exchange sweep and fit a per-platform fusion-cost profile
    (plan/fusion_cost.profile_from_exchange_sweep) the engine loads via
    the PRESTO_TPU_FUSION_PROFILE env var or the `fusion_profile`
    session property.  Default output: fusion_profile_<platform>.json
    next to this script.

    `--multiproc` adds the dcn lane (dcn_sweep subprocess meshes) and
    writes `fusion_profile_<platform>-multiproc.json` — the numbers
    that seed DEFAULT_PROFILES["cpu-multiproc"]; on a TPU pod the same
    flag measures the real DCN fabric and replaces the documented
    tpu dcn priors."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import presto_tpu  # noqa: F401  (x64 + compile cache)
    from presto_tpu.plan import fusion_cost as FC

    rng = np.random.default_rng(0)
    rtt = timed(jax.jit(lambda x: x + 1.0), jnp.float32(1.0))

    def per_iter(t):
        return max(t - rtt, 1e-9) / K

    platform = jax.devices()[0].platform
    sweep = exchange_sweep(per_iter, rng)
    if multiproc:
        for label, cell in dcn_sweep().items():
            sweep.setdefault(label, {}).update(cell)
        platform = f"{platform}-multiproc"
    prof = FC.profile_from_exchange_sweep(sweep, platform)
    prof["calibrated_from"] = "tools/roofline.py --calibrate (exchange sweep)"
    prof["n_devices"] = len(jax.devices())
    prof["sweep"] = sweep
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            f"fusion_profile_{platform}.json")
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(prof, f, indent=1, sort_keys=True)
    print(json.dumps({"profile": {k: v for k, v in prof.items()
                                  if k != "sweep"},
                      "path": out_path}), flush=True)
    return prof


def fleet_sweep(max_coord=4):
    """`tools/roofline.py --fleet [N]`: coordinator-dispatch saturation
    sweep for the multi-coordinator fleet (server/fleet.py, ISSUE 16).

    The serving tier's admission gate (concurrency slots + queue) makes
    a SINGLE front door admission-bound long before the executor is
    compute-bound; this sweep measures aggregate EXECUTE throughput as
    coordinators are added — in-process servers over ONE shared catalog
    and one FleetDirectory, signature-affinity proxying on — and reports
    where the marginal door stops paying (<10% QPS gain), i.e. where
    dispatch has saturated the machine rather than the admission gate.
    Prints ONE JSON line; the committed scaling record is SERVE_r03.json
    (bench.py --serve --coordinators N)."""
    import threading

    import numpy as np

    import presto_tpu
    from presto_tpu import types as T
    from presto_tpu.client import connect_http
    from presto_tpu.server import PrestoTpuServer
    from presto_tpu.server import fleet as FL

    nrow, clients, per_client = 100_000, 8, 25
    out = {"metric": "fleet_dispatch_saturation", "rows": nrow,
           "clients": clients, "per_client": per_client,
           "cores": os.cpu_count()}

    def one_leg(ncoord):
        d = FL.FleetDirectory()
        servers = []
        base = None
        for i in range(ncoord):
            s = presto_tpu.connect(coalesce_max_batch=4)
            if base is None:
                base = s
                s.catalog.register_memory(
                    "t", {"k": T.BIGINT, "x": T.DOUBLE},
                    {"k": np.arange(nrow, dtype=np.int64),
                     "x": np.arange(nrow, dtype=np.float64) * 1.5})
            else:
                s.catalog = base.catalog
            srv = PrestoTpuServer(s).start()
            m = d.join(f"c{i}", srv.uri)
            srv.fleet = m
            srv.serving.attach_fleet(m)
            servers.append(srv)
        try:
            connect_http(servers[0].uri).execute(
                "PREPARE fq FROM SELECT count(*) c, sum(x) s FROM t "
                "WHERE k < ?")
            for srv in servers:  # per-door warm (compile + route maps)
                connect_http(srv.uri).execute("EXECUTE fq USING 10")
            lat, errs = [], []

            def run(cid):
                uri = servers[cid % ncoord].uri
                for i in range(per_client):
                    t0 = time.perf_counter()
                    try:
                        connect_http(uri).execute(
                            f"EXECUTE fq USING {100 + cid * 997 + i}"
                        ).fetchall()
                        lat.append(time.perf_counter() - t0)
                    except Exception as e:  # noqa: BLE001
                        errs.append(str(e))

            ths = [threading.Thread(target=run, args=(c,))
                   for c in range(clients)]
            t0 = time.perf_counter()
            for th in ths:
                th.start()
            for th in ths:
                th.join()
            wall = time.perf_counter() - t0
            lat.sort()
            return {"coordinators": ncoord,
                    "queries": len(lat), "failures": len(errs),
                    "qps": round(len(lat) / wall, 1),
                    "p50_ms": round(lat[len(lat) // 2] * 1000, 1),
                    "p99_ms": round(lat[int(len(lat) * 0.99)] * 1000, 1)}
        finally:
            for srv in servers:
                srv.stop()

    legs, prev_qps, saturated_at = {}, None, None
    n = 1
    while n <= max_coord:
        leg = one_leg(n)
        legs[f"c{n}"] = leg
        if prev_qps is not None and saturated_at is None \
                and leg["qps"] < prev_qps * 1.10:
            saturated_at = n  # the marginal door stopped paying
        prev_qps = leg["qps"]
        n *= 2
    out["legs"] = legs
    out["saturated_at_coordinators"] = saturated_at
    print(json.dumps(out), flush=True)
    return out


def sketch_sweep(per_iter, rng, nexps=(20, 22, 23)):
    """Sketch economics: exact distinct shuffle vs mergeable HLL states.

    Anchors the SKETCH lane (plan/agg_strategy.py, plan/distribute.py,
    plan/fusion_cost.py): per rows x cardinality cell, the exact leg is
    what a distributed count(DISTINCT x) must execute — NCHUNK per-shard
    dedup passes, a repartition of every surviving distinct value, one
    final grouping pass over the union — while the hll leg is what the
    sketch decomposition emits instead: per-shard hll_partial register
    rows folded by ONE elementwise-max merge (the op that lowers to
    lax.pmax on a fused mesh).  The exchange payloads are static facts
    of the two plans, not measurements: the exact edge ships up to
    per-shard-distinct x 8B values, the sketch edge always ships
    NCHUNK x m register bytes regardless of cardinality — that
    constant-size edge is the whole point, so it is recorded next to
    the measured compute."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from presto_tpu import types as PT
    from presto_tpu.batch import Column as PCol
    from presto_tpu.exec import kernels as KK

    NCHUNK = 8
    M = 1024  # the engine's default register count (~3.25% std error)
    sout = {"m_registers": M, "nchunk": NCHUNK}
    for nexp in nexps:
        n = 1 << nexp
        rows_c = n // NCHUNK
        cell = {}
        for ndv, label in ((1_000, "1k"), (100_000, "100k"),
                           (10_000_000, "10M")):
            keys = jnp.asarray(rng.integers(0, ndv, n).astype(np.int64))
            h = KK.hll_hash64(PCol(keys, None, PT.BIGINT, None))
            exact_ndv = int(np.unique(np.asarray(keys)).size)
            # static capacities the exact plan must provision: per-shard
            # distinct bound, then the union of all shards' survivors
            ccap = min(1 << max(min(ndv, rows_c) - 1, 1).bit_length(),
                       rows_c)
            gcap = min(1 << max(min(ndv, n) - 1, 1).bit_length(), n)

            @jax.jit
            def exact_leg(k):
                def body(i, s):
                    pk_parts = []
                    for c in range(NCHUNK):
                        kc = lax.dynamic_slice(k, (c * rows_c,),
                                               (rows_c,)) + s
                        gid, rep, ex, ov = KK.group_ids_static(kc, ccap)
                        pk_parts.append(kc[rep])
                    pk = jnp.concatenate(pk_parts)
                    gid, rep, ex, ov = KK.group_ids_static(pk, gcap)
                    # loop-carried data dependence: XLA cannot hoist
                    return ((rep[0] ^ gid[0]) & 1).astype(jnp.int64)
                return lax.fori_loop(0, K, body, jnp.int64(0))

            @jax.jit
            def hll_leg(h):
                def body(i, s):
                    hh = h ^ s
                    ones = jnp.ones((rows_c,), bool)
                    zg = jnp.zeros((rows_c,), jnp.int32)
                    regs = []
                    for c in range(NCHUNK):
                        hc = lax.dynamic_slice(hh, (c * rows_c,),
                                               (rows_c,))
                        regs.append(KK.hll_partial(hc, ones, zg, 1, m=M))
                    R = jnp.concatenate(regs)  # (NCHUNK, M) partials
                    est = KK.hll_merge_estimate(
                        R, None, jnp.zeros((NCHUNK,), jnp.int32), 1)
                    return (est[0] & 1).astype(jnp.uint64)
                return lax.fori_loop(0, K, body, jnp.uint64(0))

            # accuracy sanity next to the timing: one unperturbed
            # estimate vs the true cardinality of this cell's data
            regs0 = KK.hll_partial(h, jnp.ones((n,), bool),
                                   jnp.zeros((n,), jnp.int32), 1, m=M)
            est0 = int(KK.hll_merge_estimate(
                regs0, None, jnp.zeros((1,), jnp.int32), 1)[0])
            cell[f"ndv{label}"] = {
                "exact_ms": round(
                    per_iter(timed(exact_leg, keys)) * 1000, 2),
                "hll_ms": round(per_iter(timed(hll_leg, h)) * 1000, 2),
                "exact_exchange_kb": round(NCHUNK * ccap * 8 / 1024, 1),
                "hll_exchange_kb": round(NCHUNK * M / 1024, 1),
                "hll_err_pct": round(
                    abs(est0 - exact_ndv) / max(exact_ndv, 1) * 100, 2),
            }
        sout[f"n{n >> 20}M"] = cell
    return sout


def sketch_anchor(nexps):
    """Standalone `--sketch` entry: run ONLY the sketch sweep and print
    one JSON line.  main() includes the sweep in the full roofline; this
    entry exists so the docs/PERF.md anchor can be re-measured on a CPU
    host without paying for the whole sweep (ROOFLINE_K overrides the
    iteration count the way the committed agg anchor used K=5)."""
    global K
    K = int(os.environ.get("ROOFLINE_K", K))
    import jax
    import jax.numpy as jnp
    import numpy as np

    import presto_tpu  # noqa: F401  (x64 + compile cache)

    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    rtt = timed(jax.jit(lambda x: x + 1.0), jnp.float32(1.0))

    def per_iter(t):
        return max(t - rtt, 1e-9) / K

    out = {"device": str(dev), "platform": dev.platform, "iters": K,
           "sketch": sketch_sweep(per_iter, rng, nexps)}
    print(json.dumps(out), flush=True)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    import presto_tpu  # noqa: F401  (x64 + compile cache)
    from presto_tpu.exec import kernels as KK

    dev = jax.devices()[0]
    out = {"device": str(dev), "platform": dev.platform, "iters": K}

    rng = np.random.default_rng(0)
    rtt = timed(jax.jit(lambda x: x + 1.0), jnp.float32(1.0))
    out["rtt_ms"] = round(rtt * 1000, 1)

    def per_iter(t):
        return max(t - rtt, 1e-9) / K

    # --- stream bandwidth: read 2 arrays per iteration ----------------
    n = 1 << 24  # 16M f32 = 64MB per array
    b = jnp.asarray(rng.normal(size=n).astype(np.float32))
    c = jnp.asarray(rng.normal(size=n).astype(np.float32))

    @jax.jit
    def stream(b, c):
        def body(i, acc):
            return acc + jnp.sum(b + c * (1.0 + acc))  # carried dep
        return lax.fori_loop(0, K, body, jnp.float32(0.0))

    t = per_iter(timed(stream, b, c))
    out["stream_read_gbps"] = round(2 * 4 * n / t / 1e9, 1)

    # --- sort throughput (i32 / i64 keys) -----------------------------
    base32 = jnp.asarray(rng.integers(0, 1 << 30, n).astype(np.int32))

    @jax.jit
    def sort_loop(x):
        def body(i, s):
            return jnp.sort(x ^ s)[0]  # dep via s; fresh sort per iter
        return lax.fori_loop(0, K, body, jnp.int32(0))

    t = per_iter(timed(sort_loop, base32))
    out["sort_i32_mrows_s"] = round(n / t / 1e6, 1)
    base64_ = jnp.asarray(rng.integers(0, 1 << 62, n))

    @jax.jit
    def sort_loop64(x):
        def body(i, s):
            return jnp.sort(x ^ s)[0]
        return lax.fori_loop(0, K, body, jnp.int64(0))

    t = per_iter(timed(sort_loop64, base64_))
    out["sort_i64_mrows_s"] = round(n / t / 1e6, 1)

    # --- gather family: random vs blocked vs sort-order ---------------
    # Pins the routing constants in exec/gather.py (the crossover where
    # sorted staging beats the flat packed gather, and where the Pallas
    # VMEM-window kernel beats the plain ascending gather).  Swept over
    # index count x row width; each cell is ns/index so the table reads
    # directly against the ~45ns/random-index constant from the round-5
    # profile.
    from presto_tpu.exec import gather as GG

    nsrc = 1 << 23  # 8M source rows, the SF100 chunk shape
    gout = {}
    for width in (1, 2, 4, 8):
        src = jnp.asarray(
            rng.integers(0, 1 << 32, (nsrc, width)).astype(np.uint32))
        for mexp in (20, 22, 23):
            m = 1 << mexp
            ridx = jnp.asarray(rng.integers(0, nsrc, m).astype(np.int32))
            sidx = jnp.sort(ridx)

            @jax.jit
            def rand_loop(src, ridx):
                def body(i, s):
                    return src[(ridx + s) % nsrc][0, 0].astype(jnp.int32)
                return lax.fori_loop(0, K, body, jnp.int32(0))

            @jax.jit
            def sorted_loop(src, sidx):
                def body(i, s):
                    return src[jnp.clip(sidx + s, 0, nsrc - 1)][0, 0] \
                        .astype(jnp.int32)
                return lax.fori_loop(0, K, body, jnp.int32(0))

            @jax.jit
            def blocked_loop(src, sidx):
                def body(i, s):
                    out = GG.staged_gather(
                        src, jnp.clip(sidx + s, 0, nsrc - 1))
                    return out[0, 0].astype(jnp.int32)
                return lax.fori_loop(0, K, body, jnp.int32(0))

            cell = {}
            cell["random_ns_per_idx"] = round(
                per_iter(timed(rand_loop, src, ridx)) / m * 1e9, 2)
            cell["sorted_ns_per_idx"] = round(
                per_iter(timed(sorted_loop, src, sidx)) / m * 1e9, 2)
            cell["blocked_ns_per_idx"] = round(
                per_iter(timed(blocked_loop, src, sidx)) / m * 1e9, 2)
            gout[f"w{width}_m{m >> 20}M"] = cell
    out["gather"] = gout

    # sort-order materialization overhead: the planning sort + the
    # co-sort home, i.e. what request-order staging adds over presorted
    m = 1 << 23
    ridx = jnp.asarray(rng.integers(0, nsrc, m).astype(np.int32))

    @jax.jit
    def plan_loop(ridx):
        def body(i, s):
            sidx, pos = lax.sort(
                (ridx ^ s, jnp.arange(m, dtype=jnp.int32)), num_keys=1)
            return sidx[0] + pos[0]
        return lax.fori_loop(0, K, body, jnp.int32(0))

    out["gather_plan_sort_ms"] = round(
        per_iter(timed(plan_loop, ridx)) * 1000, 1)

    # --- ordering economics: sorted vs unsorted grouping / join build --
    # Anchors the ordering-aware routing (plan/properties.py): what a
    # grouping pass costs when the key arrives presorted (run-boundary
    # scan, no sort, no unpermute) vs the sort path, and what the
    # presorted-build join saves (1 of 3 sorts), per key count.
    oout = {}
    for nexp in (20, 22, 23):
        ng = 1 << nexp
        skey = jnp.asarray(np.sort(rng.integers(0, ng >> 3, ng))
                           .astype(np.int32))
        sel = jnp.ones((ng,), bool)

        @jax.jit
        def grp_sorted_path(k):
            def body(i, s):
                gid, rep, ex, ov = KK.group_ids_static(jnp.abs(k) + s,
                                                       1 << 17)
                return gid[0] + rep[0]
            return lax.fori_loop(0, K, body, jnp.int32(0))

        @jax.jit
        def grp_presorted(k):
            def body(i, s):
                gid, rep, ex, ov, g = KK.group_ids_presorted_static(
                    jnp.abs(k) + s, 1 << 17)
                return gid[0] + rep[0]
            return lax.fori_loop(0, K, body, jnp.int32(0))

        cell = {}
        cell["group_sort_ms"] = round(
            per_iter(timed(grp_sorted_path, skey)) * 1000, 2)
        cell["group_presorted_ms"] = round(
            per_iter(timed(grp_presorted, skey)) * 1000, 2)
        oout[f"n{ng >> 20}M"] = cell
    # presorted-build join at the Q3 shape
    npr_, nb_ = 6_000_000, 1_500_000
    probe_ = jnp.asarray(rng.integers(0, nb_, npr_).astype(np.int32))
    build_ = jnp.asarray(np.arange(nb_, dtype=np.int32))
    ident = jnp.arange(nb_, dtype=jnp.int32)

    @jax.jit
    def bp_presorted_loop(build, probe):
        def body(i, s):
            order, lb, ub = KK.build_probe(build, probe ^ s,
                                           build_order=ident)
            return (ub[0] - lb[0]).astype(jnp.int32)
        return lax.fori_loop(0, K, body, jnp.int32(0))

    oout["build_probe_presorted_q3_ms"] = round(
        per_iter(timed(bp_presorted_loop, build_, probe_)) * 1000, 1)
    out["ordering"] = oout

    # --- aggregation economics: reduction ratio x strategy ------------
    # Anchors plan/agg_strategy.py: what one GROUP BY pass costs under
    # each strategy as the partial stage's reduction ratio (rows /
    # groups) varies.  two_phase = 8 per-chunk partial groupings + a
    # final merge over the partial outputs (the chunked/cluster
    # pipeline); final_only = ONE global grouping pass (what the
    # runtime bypass degenerates to — pass-through rows cost nothing to
    # produce); presorted = the PR-3 run-boundary scan (no sort at
    # all).  The partial_agg_min_reduction default comes from the
    # measured two_phase/final_only crossover: below it the partial
    # stage costs a full grouping pass per chunk and buys back almost
    # nothing in the final stage.
    aout = {}
    crossovers = []
    NCHUNK = 8
    for nexp in (20, 22, 23):  # 1M / 4M / 8M keys
        n = 1 << nexp
        acell = {}
        for red in (1, 2, 10, 100):
            ndv = max(n // red, 1)
            keys = jnp.asarray(rng.integers(0, ndv, n).astype(np.int32))
            skeys = jnp.asarray(np.sort(np.asarray(keys)))
            vals = jnp.asarray(rng.normal(size=n).astype(np.float32))
            gcap = min(1 << max(ndv - 1, 1).bit_length(), n)
            ccap = min(gcap, n // NCHUNK)  # per-chunk groups bound
            rows_c = n // NCHUNK

            @jax.jit
            def two_phase(k, v):
                def body(i, s):
                    pk_parts = []
                    pv_parts = []
                    for c in range(NCHUNK):
                        kc = lax.dynamic_slice(k, (c * rows_c,),
                                               (rows_c,)) + s
                        vc = lax.dynamic_slice(v, (c * rows_c,),
                                               (rows_c,))
                        gid, rep, ex, ov = KK.group_ids_static(kc, ccap)
                        pv_parts.append(KK.segment_sum(vc, gid, ccap))
                        pk_parts.append(kc[rep])
                    pk = jnp.concatenate(pk_parts)
                    pv = jnp.concatenate(pv_parts)
                    gid, rep, ex, ov = KK.group_ids_static(pk, gcap)
                    fin = KK.segment_sum(pv, gid, gcap)
                    # real loop-carried data dependence: XLA cannot
                    # hoist or elide the grouping passes
                    return (rep[0] ^ fin[0].astype(jnp.int32)) & 1
                return lax.fori_loop(0, K, body, jnp.int32(0))

            @jax.jit
            def final_only(k, v):
                def body(i, s):
                    gid, rep, ex, ov = KK.group_ids_static(k + s, gcap)
                    fin = KK.segment_sum(v, gid, gcap)
                    return (rep[0] ^ fin[0].astype(jnp.int32)) & 1
                return lax.fori_loop(0, K, body, jnp.int32(0))

            @jax.jit
            def presorted(k, v):
                def body(i, s):
                    gid, rep, ex, ov, g = KK.group_ids_presorted_static(
                        k + s, gcap)
                    fin = KK.segment_sum(v, gid, gcap)
                    return (rep[0] ^ fin[0].astype(jnp.int32)) & 1
                return lax.fori_loop(0, K, body, jnp.int32(0))

            cell = {
                "two_phase_ms": round(
                    per_iter(timed(two_phase, keys, vals)) * 1000, 2),
                "final_only_ms": round(
                    per_iter(timed(final_only, keys, vals)) * 1000, 2),
                "presorted_ms": round(
                    per_iter(timed(presorted, skeys, vals)) * 1000, 2),
            }
            if cell["final_only_ms"] < cell["two_phase_ms"]:
                crossovers.append(red)
            acell[f"r{red}x"] = cell
        aout[f"n{n >> 20}M"] = acell
    # the largest reduction ratio at which single-phase still beat
    # two-phase: the bypass threshold should sit just above ratio 1
    # (never flip a genuinely reducing partial) but below the smallest
    # measured win — the committed default is 1.3
    aout["single_phase_won_at_ratios"] = sorted(set(crossovers))
    out["agg"] = aout

    # --- sketch economics: exact distinct shuffle vs HLL merge --------
    # (sketch_sweep above; `--sketch` re-measures it standalone)
    out["sketch"] = sketch_sweep(per_iter, rng)

    # --- compile economics: compile-ms vs fragment count x mult -------
    # Frames the exec/compile_cache.py design: what a cold chunked plan
    # pays in XLA compiles (per fragment, per bound-mult variant) and
    # what the persistent disk cache gives back on the next process.
    # Each "fragment" is a filter->group->reduce chain at a distinct
    # static capacity (mult quantizes capacity, so each mult variant is
    # a fresh executable — exactly the chunked runner's key structure).
    from presto_tpu.exec import compile_cache as CC

    def fragment_fn(cap):
        def fn(x, key):
            sel = x > 0.0
            gid = jnp.clip(key, 0, 255)
            v = jnp.where(sel, x * 1.0001 + 3.0, 0.0)
            sums = jax.ops.segment_sum(v, gid, num_segments=256)
            top = jax.lax.top_k(jnp.where(sel, x, -jnp.inf),
                                min(cap, x.shape[0]))[0]
            return sums, top, jnp.sum(sel)
        return fn

    n = 1 << 20
    xa = jnp.asarray(rng.normal(size=n).astype(np.float32))
    ka = jnp.asarray(rng.integers(0, 256, n).astype(np.int32))
    # persist even sub-0.2s compiles so the cached leg measures the
    # disk-served path at this sweep's program sizes, and use a FRESH
    # cache dir so the uncached leg is honestly uncached
    import tempfile

    jax.config.update("jax_compilation_cache_dir",
                      tempfile.mkdtemp(prefix="roofline_cc_"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    cout = {}
    for nfrag in (1, 2, 4):
        for mult in (1, 4):
            caps = [1024 * mult + 128 * i for i in range(nfrag)]

            def compile_all():
                t0 = time.perf_counter()
                for cap in caps:
                    CC.build_jit(fragment_fn(cap), example=(xa, ka))
                return (time.perf_counter() - t0) * 1000

            uncached = compile_all()   # fresh HLO: full XLA compile
            jax.clear_caches()         # drop in-memory, keep disk
            # trace again, executable loads from the persistent cache
            cached = compile_all()
            cout[f"f{nfrag}_m{mult}"] = {
                "uncached_ms": round(uncached, 1),
                "cached_ms": round(cached, 1)}
    cout["counters"] = {k: round(v, 1) if isinstance(v, float) else v
                        for k, v in CC.stats().items()}
    out["compile"] = cout

    # --- dynamic filtering: probe selectivity x membership structure --
    # Pins the routing constants in exec/kernels.py (RF_EXACT_MAX, bloom
    # sizing): what the probe-side mask costs per structure at q17-like
    # shapes (6M-row probe, 16k-key build), and what the downstream join
    # gets back when the mask's selectivity lets the probe COMPACT to a
    # fraction of its capacity before build_probe (on this engine the
    # static join cost scales with capacity, so compaction is where
    # pruned rows turn into wall-clock).  Swept at 1/10/50/90% probe
    # selectivity; "off" is the unfiltered join baseline.
    from presto_tpu import types as PT
    from presto_tpu.batch import Column as PCol

    dout = {}
    nprobe_df = 1 << 22
    nbuild_df = 1 << 14
    dsel = jnp.ones((nbuild_df,), bool)
    for pct in (1, 10, 50, 90):
        # build keys live in the first pct% of the probe key domain, so
        # P(probe row survives) == pct/100 exactly
        dom = 1 << 20
        cut = max(dom * pct // 100, 1)
        bvals = jnp.asarray(rng.integers(0, cut, nbuild_df))
        pvals = jnp.asarray(rng.integers(0, dom, nprobe_df))
        bcol = PCol(bvals, None, PT.BIGINT, None)
        pcol = PCol(pvals, None, PT.BIGINT, None)
        cell = {}
        for structure in ("exact", "bloom"):
            summary = KK.rf_build(bcol, dsel, structure=structure)

            @jax.jit
            def probe_loop(pv):
                def body(i, s):
                    m = KK.rf_probe(summary,
                                    PCol(pv ^ s, None, PT.BIGINT, None))
                    return jnp.sum(m).astype(jnp.int64)

                return lax.fori_loop(0, K, body, jnp.int64(0))

            cell[f"{structure}_probe_ms"] = round(
                per_iter(timed(probe_loop, pvals)) * 1000, 2)
        # downstream: full-capacity join (off) vs masked+compacted join
        mask = KK.rf_probe(KK.rf_build(bcol, dsel, structure="exact"),
                           pcol)
        ncap = 1 << max(int(np.ceil(np.log2(nprobe_df * pct / 100))), 12)
        idx = KK.nonzero_i32(mask, ncap, 0)
        pkept = pvals[idx]
        sb = jnp.sort(bvals)

        @jax.jit
        def join_full(pv):
            def body(i, s):
                _o, lb, ub = KK.build_probe(sb, pv ^ s)
                return (ub[0] - lb[0]).astype(jnp.int32)

            return lax.fori_loop(0, K, body, jnp.int32(0))

        cell["join_off_ms"] = round(
            per_iter(timed(join_full, pvals)) * 1000, 2)
        cell["join_filtered_ms"] = round(
            per_iter(timed(join_full, pkept)) * 1000, 2)
        dout[f"sel{pct}"] = cell
    out["dynfilter"] = dout

    # --- exchange economics: host HTTP shuffle vs in-trace all_to_all --
    # (exchange_sweep above; `--calibrate` fits it into the fusion-cost
    # profile plan/fusion_cost.py loads)
    out["exchange"] = exchange_sweep(per_iter, rng)

    # --- query coalescing: B solo launches vs ONE vmap-batched launch -
    # Anchors the coalescer defaults (server/serving.py coalesce_window_
    # ms / coalesce_max_batch) with measurements instead of guesses:
    # what B separate dispatches of the prepared point-lookup shape
    # (q6-class filter + two reductions with a scalar parameter) cost
    # vs ONE jax.vmap-of-the-same-trace launch at batch B — on a
    # tunneled TPU the solo column pays B round trips, the batched
    # column one — plus the pow2 padding discipline's waste (wall at
    # the padded bucket vs at the exact batch size).  Honest CPU
    # caveat (docs/PERF.md round 16): on CPU a single reduction
    # already saturates every core and dispatch costs ~40us, so the
    # solo column WINS here — the sweep exists to measure the
    # crossover on real chips, where per-dispatch overhead is ~ms.
    nrow_c = 1 << 16  # the serving bench's point-lookup scan scale
    ckeys = jnp.asarray(rng.integers(0, nrow_c, nrow_c).astype(np.int64))
    cvals = jnp.asarray(rng.normal(size=nrow_c))

    def point_fn(k):
        m = ckeys == k
        return (jnp.sum(m.astype(jnp.int64)),
                jnp.sum(jnp.where(m, cvals, 0.0)))

    solo_j = jax.jit(point_fn)

    def solo_wall(nb):
        ks = [jnp.int64((i * 7919) % nrow_c) for i in range(nb)]
        float(solo_j(ks[0])[0])  # warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for k in ks:
                c_, _s = solo_j(k)
                float(c_)  # force each launch home, like a real EXECUTE
            best = min(best, time.perf_counter() - t0)
        return best

    def batched_wall(nb):
        ks = jnp.asarray([(i * 7919) % nrow_c for i in range(nb)],
                         dtype=jnp.int64)
        f = jax.jit(jax.vmap(point_fn))  # one executable per batch size
        float(f(ks)[0][0])  # warm (the bucket's one-time compile)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            c_, _s = f(ks)
            float(c_[0])
            best = min(best, time.perf_counter() - t0)
        return best

    coout = {}
    for nb in (1, 2, 4, 8, 16, 32):
        sw = solo_wall(nb)
        bw = batched_wall(nb)
        coout[f"b{nb}"] = {"solo_ms": round(sw * 1000, 2),
                           "vmap_ms": round(bw * 1000, 2),
                           "speedup": round(sw / bw, 2)}
    pad = {}
    for nb in (3, 5, 9):
        exact = batched_wall(nb)
        bucket = batched_wall(1 << (nb - 1).bit_length())
        pad[f"b{nb}"] = {"exact_ms": round(exact * 1000, 2),
                         "padded_ms": round(bucket * 1000, 2),
                         "pad_overhead": round(bucket / exact, 2)
                         if exact else None}
    out["coalesce"] = {"rows": nrow_c, "batch": coout, "pad_waste": pad}

    # --- build_probe at TPC-H Q3 shape: 6M probe, 1.5M build ----------
    npr, nb = 6_000_000, 1_500_000
    probe = jnp.asarray(rng.integers(0, nb, npr).astype(np.int32))
    build = jnp.asarray(np.arange(nb, dtype=np.int32))

    @jax.jit
    def bp_loop(build, probe):
        def body(i, s):
            order, lb, ub = KK.build_probe(build, probe ^ s)
            return (ub[0] - lb[0]).astype(jnp.int32)
        return lax.fori_loop(0, K, body, jnp.int32(0))

    t = per_iter(timed(bp_loop, build, probe))
    out["build_probe_q3_shape_ms"] = round(t * 1000, 1)
    out["build_probe_mrows_s"] = round((npr + nb) / t / 1e6, 1)

    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    if "--dcn-child" in sys.argv:
        args = [a for a in sys.argv[1:] if not a.startswith("--")]
        dcn_child(args[0], int(args[1]), int(args[2]), int(args[3]))
    elif "--calibrate" in sys.argv:
        args = [a for a in sys.argv[1:] if not a.startswith("--")]
        calibrate(args[0] if args else None,
                  multiproc="--multiproc" in sys.argv)
    elif "--fleet" in sys.argv:
        args = [a for a in sys.argv[1:] if not a.startswith("--")]
        fleet_sweep(int(args[0]) if args else 4)
    elif "--sketch" in sys.argv:
        args = [a for a in sys.argv[1:] if not a.startswith("--")]
        sketch_anchor(tuple(int(a) for a in args) or (20, 22, 23))
    else:
        main()
