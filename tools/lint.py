"""In-repo static-analysis gate (round-4; reference: the build-time
error-prone + checkstyle + modernizer stack wired into the root pom —
src/checkstyle/checkstyle.xml).  No third-party linters ship in this
environment, so the gate is a small AST checker covering the
error-prone-class mistakes that bite this codebase:

- syntax (compileall)
- unused imports (module scope; `# noqa` opt-out per line)
- bare `except:` (swallows KeyboardInterrupt/SystemExit)
- mutable default arguments
- `== None` / `!= None` comparisons
- re-defined top-level functions/classes in one module

Run: python tools/lint.py [paths...]   (exit 1 on findings)
"""

from __future__ import annotations

import ast
import os
import sys


def _noqa_lines(src: str):
    return {i + 1 for i, line in enumerate(src.splitlines())
            if "# noqa" in line}


def check_file(path: str):
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"syntax error: {e.msg}")]
    noqa = _noqa_lines(src)
    problems = []

    # ---- imports: collect bindings and usages -----------------------
    imports = {}  # name -> (lineno, display)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imports[name] = (node.lineno, a.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue  # compiler directive, not a binding
            for a in node.names:
                if a.name == "*":
                    continue
                name = a.asname or a.name
                imports[name] = (node.lineno, f"{node.module}.{a.name}")
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            v = node
            while isinstance(v, ast.Attribute):
                v = v.value
            if isinstance(v, ast.Name):
                used.add(v.id)
    # names re-exported via __all__ count as used
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__" \
                        and isinstance(node.value, (ast.List, ast.Tuple)):
                    for e in node.value.elts:
                        if isinstance(e, ast.Constant) \
                                and isinstance(e.value, str):
                            used.add(e.value)
    for name, (lineno, disp) in imports.items():
        if name not in used and lineno not in noqa:
            problems.append((path, lineno, f"unused import: {disp}"))

    # ---- bare except / mutable defaults / == None -------------------
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None \
                and node.lineno not in noqa:
            problems.append((path, node.lineno,
                             "bare `except:` (catches SystemExit)"))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in list(node.args.defaults) \
                    + [x for x in node.args.kw_defaults if x is not None]:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)) \
                        and d.lineno not in noqa:
                    problems.append(
                        (path, d.lineno,
                         f"mutable default argument in {node.name}()"))
        if isinstance(node, ast.Compare) and node.lineno not in noqa:
            for op, cmp_ in zip(node.ops, node.comparators):
                if isinstance(op, (ast.Eq, ast.NotEq)) \
                        and isinstance(cmp_, ast.Constant) \
                        and cmp_.value is None:
                    problems.append((path, node.lineno,
                                     "use `is None`, not `== None`"))

    # ---- duplicate top-level defs -----------------------------------
    seen = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.name in seen and node.lineno not in noqa:
                problems.append(
                    (path, node.lineno,
                     f"redefinition of {node.name} "
                     f"(first at line {seen[node.name]})"))
            seen[node.name] = node.lineno
    return problems


def lint(paths):
    problems = []
    for root in paths:
        if os.path.isfile(root):
            problems += check_file(root)
            continue
        for dirpath, _dirs, files in os.walk(root):
            for fn in files:
                if fn.endswith(".py"):
                    problems += check_file(os.path.join(dirpath, fn))
    return problems


def main(argv=None):
    paths = (argv or sys.argv[1:]) or ["presto_tpu"]
    problems = lint(paths)
    for path, lineno, msg in sorted(problems):
        print(f"{path}:{lineno}: {msg}")
    print(f"{len(problems)} finding(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
